"""Device-mesh bootstrap.

Replaces the reference's cluster plumbing with a :class:`jax.sharding.Mesh`:

* ``distkeras/networking.py -> determine_host_address()`` (driver IP discovery for the
  socket parameter server) has no equivalent — collective routing is XLA's job.
* ``distkeras/trainers.py -> Trainer(num_workers=...)`` (Spark partition count) maps to
  the size of the ``'data'`` mesh axis: one worker replica per chip (or per mesh row
  when model axes are in play).
* ``spark-submit`` / ``job_deployment.py`` maps to :func:`distributed_initialize`
  (multi-host DCN bootstrap via ``jax.distributed``).

Axis conventions (fixed names so shardings compose across the package):

* ``data``   — data parallel; one dist-keras "worker" per slice.
* ``model``  — tensor parallel (sharded weight matrices).
* ``seq``    — sequence/context parallel (ring attention).
* ``pipe``   — pipeline parallel (stage axis).
* ``expert`` — expert parallel (MoE).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

P = PartitionSpec


def device_count() -> int:
    """Number of addressable accelerator chips (Spark ``num_workers`` analogue)."""
    return jax.device_count()


def distributed_initialize(**kwargs) -> None:
    """Multi-host bootstrap over DCN (``jax.distributed.initialize`` passthrough).

    The reference reached other hosts via Spark's JVM scheduler + ssh
    (``job_deployment.py -> Job/Punchcard``); on TPU pods the runtime handles
    cross-host wiring once this is called on every host. Safe to call when already
    initialized (no-op).
    """
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError:
        # Already initialized (or single-process run) — mirror Spark's idempotent
        # context lookup rather than erroring.
        pass


def data_mesh(num_workers: int | None = None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A 1-D mesh over the ``data`` axis — the default for every dist-keras trainer.

    ``num_workers`` mirrors ``Trainer(num_workers=...)``: take the first N devices.
    Defaults to every addressable device.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if num_workers is not None:
        if num_workers > len(devs):
            raise ValueError(
                f"num_workers={num_workers} exceeds available devices ({len(devs)})"
            )
        devs = devs[:num_workers]
    return Mesh(np.asarray(devs), (DATA_AXIS,))


def hybrid_mesh(
    axis_sizes: dict[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """An N-D mesh from ``{axis_name: size}``; one size may be -1 (inferred).

    Example: ``hybrid_mesh({'data': -1, 'model': 2})`` on 8 chips gives a 4x2 mesh.
    Axis order follows dict order; put the fastest-varying (most-communicating) axis
    last so it lands on adjacent ICI links.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if len(devs) % known != 0:
            raise ValueError(f"{len(devs)} devices not divisible by {known}")
        sizes[sizes.index(-1)] = len(devs) // known
    total = math.prod(sizes)
    if total > len(devs):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {len(devs)}")
    grid = np.asarray(devs[:total]).reshape(sizes)
    return Mesh(grid, tuple(names))


def put_global(tree, sharding):
    """``device_put`` that also works when ``sharding`` spans multiple processes.

    Single-process (the common chip-local case) this is exactly
    ``jax.device_put``. Multi-process, ``jax.device_put`` refuses shardings
    with non-addressable devices; instead every process — which by the
    data-plane contract holds the identical full host value (deterministic
    ``BatchPlan``/init) — hands each of *its* devices the shard it owns via
    :func:`jax.make_array_from_callback`, assembling one global ``jax.Array``.

    PRNG key arrays (extended dtypes) can't go through the callback path; they
    are rebuilt on-device from their raw ``key_data`` inside a tiny jitted
    program with ``out_shardings``.
    """
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    def _one(x, sh):
        if isinstance(x, jax.Array) and jax.dtypes.issubdtype(
            x.dtype, jax.dtypes.prng_key
        ):
            data = np.asarray(jax.random.key_data(x))
            impl = jax.random.key_impl(x)
            g = jax.make_array_from_callback(data.shape, sh, lambda idx: data[idx])
            return jax.jit(
                lambda d: jax.random.wrap_key_data(d, impl=impl),
                out_shardings=sh,
            )(g)
        arr = np.asarray(x)
        return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])

    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree.map(lambda x: _one(x, sharding), tree)
    # `sharding` is a pytree matching `tree` (per-leaf shardings, as
    # param_shardings produces).
    return jax.tree.map(_one, tree, sharding)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for the center variable: fully replicated across the mesh."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, *, extra_axes: int = 0) -> NamedSharding:
    """Sharding for a per-worker-stacked array: leading dim split over ``data``."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * extra_axes)))
