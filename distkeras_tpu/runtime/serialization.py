"""Model and parameter serialization.

Parity with the reference's ``distkeras/utils.py -> serialize_keras_model /
deserialize_keras_model``, which turned a Keras model into
``{'model': model.to_json(), 'weights': model.get_weights()}`` so it could be pickled
onto Spark executors. Here a model is a registered flax module class + JSON-able
constructor kwargs + a parameter pytree; the wire format is::

    MAGIC | u32 spec_len | spec JSON (class, kwargs, version) | flax msgpack params

No pickle anywhere — the payload is msgpack via ``flax.serialization``, safe to load
from untrusted storage, and the spec is plain JSON.
"""

from __future__ import annotations

import json
import struct
from typing import Any

from flax import serialization as flax_ser

MAGIC = b"DKTPU1"

# Registry of model classes usable in serialized specs; populated by
# distkeras_tpu.models.base.register_model.
MODEL_REGISTRY: dict[str, type] = {}


def register_model_class(name: str, cls: type) -> None:
    MODEL_REGISTRY[name] = cls


def get_model_class(name: str) -> type:
    try:
        return MODEL_REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown model class {name!r}; known: {sorted(MODEL_REGISTRY)}. "
            "Custom modules must be registered with "
            "distkeras_tpu.models.register_model before deserialization."
        ) from None


def serialize_params(params: Any) -> bytes:
    """Parameter pytree -> msgpack bytes (weights-only path)."""
    return flax_ser.to_bytes(params)


def deserialize_params(target: Any, data: bytes) -> Any:
    """msgpack bytes -> pytree with ``target``'s structure."""
    return flax_ser.from_bytes(target, data)


def serialize_model(model) -> bytes:
    """A ``Model`` -> self-describing bytes (architecture spec + weights).

    Format v2 packs ``{"params", "state"}`` so stateful models (carried
    BatchNorm statistics) round-trip; v1 blobs (params-only) still load.
    """
    spec = dict(model.spec())
    spec["format_version"] = 2
    spec_bytes = json.dumps(spec).encode("utf-8")
    payload = flax_ser.to_bytes(
        {"params": model.params, "state": getattr(model, "state", None) or {}})
    return MAGIC + struct.pack("<I", len(spec_bytes)) + spec_bytes + payload


def deserialize_model(data: bytes):
    """Bytes from :func:`serialize_model` -> reconstructed ``Model``."""
    from distkeras_tpu.models.base import Model  # local import: avoid cycle

    if data[: len(MAGIC)] != MAGIC:
        raise ValueError("not a distkeras_tpu serialized model (bad magic)")
    off = len(MAGIC)
    (spec_len,) = struct.unpack_from("<I", data, off)
    off += 4
    spec = json.loads(data[off : off + spec_len].decode("utf-8"))
    off += spec_len
    cls = get_model_class(spec["class"])
    module = cls.from_config(spec["kwargs"])
    restored = flax_ser.msgpack_restore(data[off:])
    if spec.get("format_version", 1) >= 2:
        params, state = restored["params"], restored["state"] or None
    else:
        params, state = restored, None
    # msgpack round-trips lists as {'0': ..., '1': ...} dicts; modules that use
    # list-shaped params (e.g. the Keras adapter) restore the structure here.
    if hasattr(module, "fix_params_structure"):
        params = module.fix_params_structure(params)
        if state is not None:
            state = {k: module.fix_params_structure(v) for k, v in state.items()}
    return Model(module=module, params=params, state=state)
