"""Run-level configuration and the typed environment-variable registry.

Two surfaces live here:

* :class:`RunConfig` — the reference keeps every hyperparameter as a trainer
  ``__init__`` kwarg (``distkeras/trainers.py``: ``num_workers``,
  ``batch_size``, ``num_epoch``, ``communication_window``, ``learning_rate``,
  ``master_port``...). The trainers keep that kwargs-first surface and
  normalize into this frozen dataclass (``Trainer.config``); the kwarg names
  remain live as properties delegating here.

* The ``DKTPU_*`` **environment registry** — the single home for every
  environment variable the framework reads. Each variable is declared once
  as an :class:`EnvVar` (name, type, default, doc, category) and read
  through the typed ``env_*`` accessors below. This is the only module
  allowed to touch ``os.environ``; the dk-check rule DK301
  (``distkeras_tpu/analysis``) enforces that, DK302 rejects undeclared
  ``DKTPU_*`` names anywhere in the package, and DK303 keeps the
  auto-generated docs tables (``python -m distkeras_tpu.analysis
  --write-env-docs``) in sync with this registry.

This module must stay importable without jax (the analyzer and the
telemetry core import it; telemetry is contractually jax-free), so the
dtype table resolves lazily.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass(frozen=True)
class RunConfig:
    batch_size: int = 32
    num_epoch: int = 1
    communication_window: int = 5
    learning_rate: float = 0.01
    num_workers: Optional[int] = None  # None -> all devices
    compute_dtype: Optional[str] = None  # "bfloat16" is MXU-native; params stay f32
    seed: int = 0
    shuffle: bool = False
    drop_remainder: bool = True

    @property
    def dtype(self):
        import jax.numpy as jnp  # lazy: keep this module importable sans jax

        return {None: None, "float32": jnp.float32,
                "bfloat16": jnp.bfloat16, "float16": jnp.float16}[
                    self.compute_dtype]

    def replace(self, **kw) -> "RunConfig":
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------------
# Environment-variable registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One declared environment variable: the registry row.

    ``kind`` is the accessor family (``bool``/``int``/``float``/``str``);
    ``default`` is what an unset or empty variable reads as (``None`` means
    "no value configured"). ``doc`` is one rendered sentence — it IS the
    docs-table cell, keep it self-contained.
    """

    name: str
    kind: str
    default: object
    doc: str
    # "observability" | "resilience" | "network" | "fleet" | "serving" |
    # "data" | "streaming" | "interop" | "sim"
    category: str


def _declare(*vars_: EnvVar) -> dict:
    reg: dict = {}
    for v in vars_:
        if v.name in reg:
            raise ValueError(f"duplicate EnvVar {v.name!r}")
        reg[v.name] = v
    return reg


ENV_REGISTRY: dict = _declare(
    EnvVar("DKTPU_TELEMETRY", "bool", True,
           "Master switch for the telemetry registry; `0` swaps every "
           "span/counter/gauge/histogram for a no-op singleton.",
           "observability"),
    EnvVar("DKTPU_TRACE", "bool", False,
           "Fleet-wide distributed tracing (`telemetry/tracing/`): commit "
           "and serve requests carry a `(trace, parent)` context across "
           "processes (capability-gated — peers without `CAPS['tracing']` "
           "see zero new bytes) and every process records span/flight "
           "evidence. Off by default: no trace ids, no extra wire fields, "
           "no span records.",
           "observability"),
    EnvVar("DKTPU_TRACE_DIR", "str", "",
           "Directory for per-process trace streams "
           "(`trace-<role>-<pid>.jsonl`, appended per span so a SIGKILL "
           "loses at most one torn line) and flight-recorder dumps "
           "(`flight-<role>-<pid>.jsonl`). Empty = fall back to "
           "`DKTPU_PS_STATE_DIR`; with neither set, spans still ride the "
           "in-memory telemetry event stream and the flight ring.",
           "observability"),
    EnvVar("DKTPU_TRACE_RING", "int", 256,
           "Flight-recorder capacity: recent telemetry events + trace "
           "spans kept in a bounded in-memory ring per process, dumped on "
           "fault injection, epoch fencing, SIGTERM, and unhandled crash.",
           "observability"),
    EnvVar("DKTPU_TRACE_ROLE", "str", "",
           "Role label (`ps`, `standby`, `shard0`, `worker1`, `serve`, "
           "...) stamped into every trace/flight/process-info record this "
           "process writes; the netps CLI and the fleet `Job` launcher set "
           "it automatically, so only hand-launched processes need it.",
           "observability"),
    EnvVar("DKTPU_TELEMETRY_ROTATE_MB", "float", 0.0,
           "Size bound (MiB) for telemetry/trace JSONL files: a file at or "
           "over the bound is rotated (atomic rename to `<path>.<n>`, "
           "generations numbered from 1) before the next append; the "
           "collector reads generations in order. 0 = no rotation "
           "(unbounded growth under streaming workloads).",
           "observability"),
    EnvVar("DKTPU_HEALTH_TARGETS", "str", "",
           "Ad-hoc scrape targets for the health plane's `MetricsHub`: "
           "`[name=]host:port` entries separated by `;` (or `,`), merged "
           "with the in-process registry fleet components populate "
           "automatically. Re-read every sweep, so targets can be added "
           "while the hub runs.",
           "observability"),
    EnvVar("DKTPU_HEALTH_INTERVAL", "float", 2.0,
           "Seconds between `MetricsHub` scrape sweeps over the registered "
           "targets (each sweep is one `stats` frame per target — no "
           "membership, no lease traffic).",
           "observability"),
    EnvVar("DKTPU_HEALTH_RING", "int", 240,
           "Points kept per metric time-series ring in the hub (per "
           "target, per metric). At the default 2 s interval, 240 points "
           "is an 8-minute window — enough to cover the default slow "
           "burn-rate window with slack.",
           "observability"),
    EnvVar("DKTPU_HEALTH_DOWN_AFTER", "int", 3,
           "Consecutive missed scrapes after which a previously-reachable "
           "target is declared down (the `target_down` sentinel fires a "
           "page alert; supervisors consulting `MetricsHub.is_down` may "
           "restart it).",
           "observability"),
    EnvVar("DKTPU_SIM_SEED", "int", 0,
           "Default RNG seed for the fleet simulator (`distkeras_tpu.sim`): "
           "every `SimEngine()` built without an explicit seed draws from "
           "one `random.Random(seed)`, so two runs of the same scenario are "
           "bit-identical. Pass `--seed` / `SimEngine(seed=...)` to "
           "override per run.",
           "sim"),
    EnvVar("DKTPU_SIM_BAND_PCT", "float", 20.0,
           "Calibration tolerance (percent) for the simulator's replay "
           "gates: `sim_drift` and the `hier_crossover` held-out "
           "predictions must land within this band of the measured "
           "throughput or the gate (and the bench-regression sentinel "
           "watching `sim_drift.within_band`) reports a miss.",
           "sim"),
    EnvVar("DKTPU_HEALTH_SLO", "str", "",
           "SLO specs for the health plane: inline JSON (starts with `[` "
           "or `{`) or a path to a JSON file. Each spec names a hub "
           "metric, a stat (`value`/`mean`/`rate`/`p99`/...), one bound "
           "(`max` or `min`), burn-rate windows (`fast_s`/`slow_s`), and "
           "a severity (`page` dumps the flight recorder on fire).",
           "observability"),
    EnvVar("DKTPU_VITALS_S", "float", 0.0,
           "Process-vitals sample interval (seconds): periodic "
           "`runtime.rss_mb`, `runtime.open_fds`, and (when jax sees a "
           "device) `device.bytes_in_use` gauges feeding the hub via the "
           "stats op. 0 = off; the netps CLI and the serving frontend "
           "start the sampler when set.",
           "observability"),
    EnvVar("DKTPU_NAN_GUARD", "bool", True,
           "On-device NaN/Inf round skip in the engine round bodies; `0` "
           "disables (poisoned rounds then propagate into the center).",
           "resilience"),
    EnvVar("DKTPU_CKPT_DIGEST", "bool", True,
           "sha256 integrity sidecars next to each checkpoint step; `0` "
           "disables writing (and therefore verified restore).",
           "resilience"),
    EnvVar("DKTPU_DIVERGENCE_RESET", "float", None,
           "Opt-in divergent-worker reset threshold: a worker whose loss "
           "strays more than this from the finite worker mean re-adopts the "
           "center. Unset = off (the default path never fetches the loss).",
           "resilience"),
    EnvVar("DKTPU_FEEDER_WARN", "float", 1.0,
           "Seconds of input-pipeline silence before the first stall "
           "warning; later warnings back off exponentially (2x, 4x, ...).",
           "resilience"),
    EnvVar("DKTPU_FEEDER_TIMEOUT", "float", 300.0,
           "Seconds of input-pipeline silence after which the RoundFeeder "
           "declares the data plane dead with `FeederStalledError`.",
           "resilience"),
    EnvVar("DKTPU_FEEDER_RETRIES", "int", 0,
           "Retries (exponential backoff) for a *failed* feeder stage call "
           "before the error propagates; 0 = off.",
           "resilience"),
    EnvVar("DKTPU_FAULTS", "str", "",
           "Fault-injection plan, `kind@round[:arg]` entries separated by "
           "`;` (e.g. `nan@3;stall@5:0.5;crash@7;seed=11`). Empty = no "
           "injection. See docs/RESILIENCE.md for the fault taxonomy.",
           "resilience"),
    EnvVar("DKTPU_FAULTS_STATE", "str", "",
           "Path to the fired-faults journal so one-shot faults (notably "
           "`kill@R`) survive the process restart they cause. Empty = "
           "in-memory only.",
           "resilience"),
    EnvVar("DKTPU_NET_TIMEOUT", "float", 30.0,
           "Per-attempt RPC deadline (seconds) for every netps network "
           "operation: connect, send, and the full reply all fit inside it.",
           "network"),
    EnvVar("DKTPU_NET_RETRIES", "int", 5,
           "Retries after the first attempt for a retryable netps RPC "
           "failure (timeout, connection loss, framing error); the typed "
           "rejections (draining, lease expired) never retry.",
           "network"),
    EnvVar("DKTPU_NET_BACKOFF", "float", 0.05,
           "Base of the netps retry backoff: each retry sleeps a "
           "full-jitter draw from [0, base * 2^attempt), capped — "
           "decorrelated, so a partition's W victims don't retry in "
           "lockstep.",
           "network"),
    EnvVar("DKTPU_NET_MAX_FRAME", "int", 1 << 30,
           "Largest wire frame (bytes) either netps side will accept; "
           "oversized frames are rejected before any allocation.",
           "network"),
    EnvVar("DKTPU_NET_INFLIGHT", "int", 1,
           "Max un-ACKed netps commits a remote worker may have in flight "
           "while it computes ahead (compute/comms overlap); 1 = the serial "
           "pull -> compute -> commit loop. Staleness accounting always "
           "reflects the realized in-flight delay.",
           "network"),
    EnvVar("DKTPU_NET_COMPRESS", "str", "none",
           "Delta codec for netps commits: `none` (f32), `bf16` (truncate), "
           "or `int8` (per-tensor scale + client-side error-feedback "
           "residual). Capability-negotiated at join — a server without the "
           "codec silently falls back to `none`.",
           "network"),
    EnvVar("DKTPU_NET_SHARDS", "int", 1,
           "Connections a netps client stripes each pull/commit's tensors "
           "across (concurrent per-shard RPCs, reassembled before "
           "fold/adopt); 1 = one socket. Negotiated at join; one logical "
           "commit keeps ONE seq across all stripes (exactly-once).",
           "network"),
    EnvVar("DKTPU_NET_TRANSPORT", "str", "tcp",
           "netps wire dialect: `tcp` (default), `shm` — colocated "
           "peers (boot-id match, negotiated in the join reply) move "
           "payloads through a shared-memory ring with a UDS doorbell — "
           "or `mesh`: same-RUNTIME peers (boot-id + pid match) fold "
           "straight into the server's device-resident center through an "
           "in-process dispatch, zero wire bytes, with the shm ring "
           "negotiated alongside as the demotion target (mesh -> shm -> "
           "tcp). Old peers, cross-process, and cross-host pairs "
           "silently stay on the lower dialects with every guarantee "
           "intact.",
           "network"),
    EnvVar("DKTPU_NET_HIER", "bool", False,
           "Hierarchical two-level folds: each `run_remote` host "
           "interposes a per-host aggregator that pre-combines its "
           "workers' commits and forwards one combined commit upstream, "
           "cutting root ingress by the worker fan-in (combined commit's "
           "pull counter = min of constituents).",
           "network"),
    EnvVar("DKTPU_NET_FAULTS", "str", "",
           "Network-fault chaos plan for the netps proxy, shm ring, "
           "remote worker loop, PS server, and fleet scheduler: "
           "`kind@frame[:arg]` entries (`delay`/`drop`/"
           "`dup`/`truncate`/`partition`/`evict`, `_r` suffix = reply "
           "direction; `shm_delay`/`shm_corrupt` hit the shared-memory "
           "ring; `ps_crash`/`ps_hang` hit the server process; `preempt` "
           "drives the FleetScheduler's forced-preemption drill; "
           "`serve_slow`/`serve_drop` hit the serving frontend's request "
           "stream; `mesh_down@R` severs the device-mesh dispatch at "
           "commit seq R, forcing the mesh->shm/TCP demotion drill; "
           "`link_down`/`link_flap` black-hole one aggregation-tree "
           "uplink, keyed by `TreeSpec.link_key(level, group)`) "
           "separated by `;`, e.g. `delay@3:0.2;drop@5;partition@7:2`. "
           "Empty = no injection. See docs/RESILIENCE.md.",
           "network"),
    EnvVar("DKTPU_TREE_SPEC", "str", "",
           "Aggregation-tree shape, bottom-up: `name:fanout[:codec]` "
           "levels separated by `,`, e.g. `host:8,pool:4,region:2` — "
           "workers flush into level-0 nodes, each level folds `fanout` "
           "children into one combined commit, the top level flushes into "
           "the root PS. A level's optional codec pins its uplinks "
           "(`region:2:int8`); otherwise each link probes its own. Empty "
           "= flat star (or the single `DKTPU_NET_HIER` level).",
           "network"),
    EnvVar("DKTPU_TREE_BUFFER", "int", 32,
           "Partition ride-through bound: combined windows a tree node "
           "buffers while its uplink is black-holed. The buffer drains "
           "in-order on heal (exactly-once end-to-end); past the bound "
           "the OLDEST windows degrade to counted, typed drops "
           "(`netps_tree_window_drop`) the staleness rule absorbs.",
           "network"),
    EnvVar("DKTPU_TREE_DEMOTE_AFTER", "int", 3,
           "Consecutive uplink transport failures before a tree node "
           "demotes that one link to plain TCP (per-link shm->TCP "
           "fallback, dedup-preserving redial); a healthy streak "
           "renegotiates back up. 0 disables auto-demotion.",
           "network"),
    EnvVar("DKTPU_PS_LEASE", "float", 10.0,
           "Membership lease (seconds) the netps server grants on join and "
           "renews on every pull/commit/heartbeat; a worker silent past it "
           "is evicted and training continues with the survivors.",
           "network"),
    EnvVar("DKTPU_PS_ENDPOINT", "str", "",
           "Endpoint(s) of a running netps parameter server: `host:port`, "
           "or a comma-separated `primary:port,standby:port` list the "
           "client walks on failure/`not_primary` (failover); async "
           "trainers use it when `remote=` is not passed explicitly "
           "(`Job` sets it for every launched worker).",
           "network"),
    EnvVar("DKTPU_PS_STATE_DIR", "str", "",
           "Directory for the netps server's durable state (write-ahead "
           "commit journal + periodic center snapshots + sha256 sidecars); "
           "a restarted server recovers center/counter/dedup state from it "
           "and in-flight commits retransmit exactly-once. Empty = "
           "in-memory only (a PS crash loses every fold).",
           "network"),
    EnvVar("DKTPU_PS_SNAPSHOT_EVERY", "int", 500,
           "Folds between netps center snapshots when a state dir is set; "
           "each snapshot rotates + compacts the journal, so on-disk state "
           "stays bounded at ~2 snapshots plus the commits between them. "
           "0 disables snapshots (journal-only, unbounded).",
           "network"),
    EnvVar("DKTPU_PS_STANDBY", "str", "",
           "`host:port` of the PRIMARY a `python -m distkeras_tpu.netps` "
           "process should run as a warm standby of: it tails the "
           "primary's journal stream over the wire (`replicate` frames), "
           "promotes itself when the primary's lease lapses, and fences "
           "the old epoch. Empty = run as a primary.",
           "network"),
    EnvVar("DKTPU_NET_AUTOTUNE", "bool", False,
           "Self-tuning data plane (`netps/tuner/`): join-time micro A/B "
           "probes pick the codec per connection, and an online control "
           "loop over the live gauges retunes compression/inflight/"
           "striping mid-run through the existing renegotiation paths, "
           "with hysteresis and an oscillation fallback to the static "
           "knobs. Explicit `DKTPU_NET_*` knobs still win where set. "
           "Off by default.",
           "network"),
    EnvVar("DKTPU_TUNE_INTERVAL", "int", 8,
           "Rounds between online-controller evaluations when "
           "`DKTPU_NET_AUTOTUNE=1` — the control loop's clock; larger "
           "values react slower but measure cleaner windows.",
           "network"),
    EnvVar("DKTPU_TUNE_COOLDOWN", "int", 16,
           "Rounds a knob rests after the controller retunes it "
           "(per-knob hysteresis) — a knob can never be retuned faster "
           "than this regardless of what the gauges say.",
           "network"),
    EnvVar("DKTPU_TUNE_PROBES", "int", 3,
           "Timed probe round trips per candidate codec in the join-time "
           "micro A/B (each carries the full center payload; the score "
           "is logical f32 bytes per second of round trip).",
           "network"),
    EnvVar("DKTPU_TUNE_MAX_RETUNES", "int", 8,
           "Total mid-run retunes the controller may take before it "
           "freezes at whatever it converged to (bounded retune rate).",
           "network"),
    EnvVar("DKTPU_TUNE_OSC_LIMIT", "int", 3,
           "Consecutive back-to-previous flips of one knob before the "
           "controller declares oscillation, restores that knob's static "
           "initial value, and freezes it for the rest of the run.",
           "network"),
    EnvVar("DKTPU_TUNE_HIER_FANIN", "int", 4,
           "Per-host worker fan-in at/above which the controller picks "
           "hierarchical aggregation over flat topology (the bench "
           "`hier_curve` crossover; below it the aggregator's combining "
           "window costs more than it saves).",
           "network"),
    EnvVar("DKTPU_TUNE_MIN_GAIN", "float", 0.1,
           "Fractional commit-rate improvement a grown worker count must "
           "show over the best smaller count for the fleet scheduler's "
           "marginal-throughput policy to keep expanding that job "
           "(`netps/tuner/fleet.py`).",
           "network"),
    EnvVar("DKTPU_TUNE_HIDDEN_FLOOR", "float", 0.5,
           "Target floor for `netps.overlap.hidden_fraction`: measured "
           "overlap below it means comms the compute loop still sees, "
           "and the controller widens inflight / shrinks the wire.",
           "network"),
    EnvVar("DKTPU_TUNE_STALE_CEIL", "float", 4.0,
           "Ceiling for `discipline.staleness_mean` (rounds): measured "
           "staleness above it means the overlap window outran the "
           "center, and the controller narrows inflight.",
           "network"),
    EnvVar("DKTPU_PS_SHARD_RULES", "str", "",
           "Partition rules for the sharded center plane: `regex=target` "
           "entries separated by `;`, first match wins, where target is a "
           "shard index (pin) or `split` (row-split across all shards); "
           "parameters matching no rule are byte-balanced greedily. Empty "
           "= fully rule-free balancing. See docs/SHARDING.md.",
           "sharding"),
    EnvVar("DKTPU_PS_SHARD_CAP_BYTES", "int", 0,
           "Per-shard byte budget (center + optimizer-state factor) the "
           "PartitionPlan must fit: tensors over the cap row-split, and a "
           "plan whose fattest shard still exceeds it is a typed "
           "`ShardPlanError` at build time — never an OOM at fold time. "
           "0 = unlimited.",
           "sharding"),
    EnvVar("DKTPU_PS_SHARD_OPT_FACTOR", "float", -1.0,
           "Optimizer-state byte multiplier the plan budgets per parameter "
           "byte (adagrad accumulators ~= 1.0): shard load = center bytes "
           "x (1 + factor). Negative = measure it from the transform's "
           "actual state leaves at launch (`plan_for_model`).",
           "sharding"),
    EnvVar("DKTPU_FLEET_CAPACITY", "int", 0,
           "Worker-slot capacity of a FleetScheduler constructed without an "
           "explicit `capacity=`; 0 = no default (the constructor then "
           "requires one).",
           "fleet"),
    EnvVar("DKTPU_FLEET_TICK", "float", 0.05,
           "Seconds between FleetScheduler passes in `run()`/`start()` "
           "(reap finished workers, fire preempt faults, place queued "
           "jobs, expand elastically).",
           "fleet"),
    EnvVar("DKTPU_FLEET_PREEMPT_GRACE", "float", 0.0,
           "Seconds a preempted worker gets to exit at a round boundary "
           "before the scheduler revokes its lease on the job's parameter "
           "server; 0 = revoke immediately (the worker's in-flight window "
           "is discarded by the eviction path, never double-folded).",
           "fleet"),
    EnvVar("DKTPU_FLEET_QUOTA", "str", "",
           "Per-tenant worker-slot quotas for a FleetScheduler constructed "
           "without explicit `quotas=`: `tenant=N` entries separated by "
           "`;` (e.g. `acme=4;bidco=2`). Empty = every tenant may use the "
           "whole pool.",
           "fleet"),
    EnvVar("DKTPU_FLEET_MAX_RESTARTS", "int", 3,
           "Per-job budget of crashed-worker restarts the FleetScheduler "
           "performs before declaring the job failed and draining it.",
           "fleet"),
    EnvVar("DKTPU_SERVE_MAX_WAIT_MS", "float", 5.0,
           "Latency budget (milliseconds) the serving micro-batcher waits "
           "to coalesce concurrent requests into one batch before "
           "dispatching whatever it holds; 0 = dispatch immediately "
           "(batch = whatever arrived together).",
           "serving"),
    EnvVar("DKTPU_SERVE_BUCKETS", "str", "1,4,16,64,256",
           "Comma-separated ascending batch-size buckets the serving "
           "frontend pads every micro-batch up to; jit compiles one "
           "program per bucket at warmup, so ragged request batches never "
           "retrace. The largest bucket is also the per-batch row cap.",
           "serving"),
    EnvVar("DKTPU_SERVE_QUEUE", "int", 256,
           "Admission-control bound on rows queued in the serving "
           "frontend; a request that would overflow it is shed with a "
           "typed `overloaded` reply BEFORE being accepted (an accepted "
           "request is never silently dropped).",
           "serving"),
    EnvVar("DKTPU_SERVE_DEADLINE_MS", "float", None,
           "Optional per-request serving deadline (milliseconds, measured "
           "from admission): a queued request older than this is answered "
           "with a typed `deadline` reply instead of being computed — "
           "shedding work nobody is waiting for anymore. Unset = no "
           "deadline.",
           "serving"),
    EnvVar("DKTPU_SERVE_POLL_S", "float", 2.0,
           "Seconds between ModelRegistry checkpoint-directory polls for "
           "hot-swap candidates; each newer intact step is restored "
           "(sha256-verified), warmup-probed, and swapped in atomically "
           "between batches.",
           "serving"),
    EnvVar("DKTPU_NO_NATIVE", "bool", False,
           "`1` disables the native (C++) data-plane kernels; every gather "
           "falls back to numpy (bit-identical, slower).",
           "data"),
    EnvVar("DKTPU_STREAM_POLL_S", "float", 0.05,
           "Seconds a FileTailSource sleeps between polls of its feed file "
           "when no complete frame is available yet (the tail-follow "
           "cadence).",
           "streaming"),
    EnvVar("DKTPU_STREAM_RECONNECT_S", "float", 10.0,
           "Cap (seconds) on a SocketSource's exponential reconnect "
           "backoff after the feed connection drops; each reconnect "
           "resumes delivery at the next undelivered record index.",
           "streaming"),
    EnvVar("DKTPU_STREAM_EVAL_FAST", "int", 64,
           "Fast (recent) window size, in committed items, of the "
           "streaming windowed eval — the numerator of the drift ratio.",
           "streaming"),
    EnvVar("DKTPU_STREAM_EVAL_SLOW", "int", 512,
           "Slow (baseline) window size, in committed items, of the "
           "streaming windowed eval — the denominator of the drift ratio.",
           "streaming"),
    EnvVar("DKTPU_STREAM_DRIFT_FACTOR", "float", 2.0,
           "Fast-window/slow-window loss ratio past which the streaming "
           "DriftWatch declares drift: the `stream:loss_divergence` page "
           "fires and checkpoint-on-drift triggers.",
           "streaming"),
    EnvVar("DKTPU_STREAM_REGRESS_FLOOR", "float", 0.25,
           "Fractional regression tolerance of the hot-swap quality gate: "
           "a candidate whose held-out loss exceeds the best accepted "
           "loss by more than this fraction is refused "
           "(rollback-on-regression).",
           "streaming"),
    EnvVar("DKTPU_STREAM_CKPT_EVERY", "int", 16,
           "Committed items between streaming center checkpoints (the "
           "hot-swap cadence); drift detection forces an immediate "
           "checkpoint regardless. 0 disables interval checkpoints.",
           "streaming"),
    EnvVar("DKTPU_STREAM_MAX_PENDING", "int", 8,
           "Backpressure bound on stream records admitted but not yet "
           "claimed by a worker; the reader blocks at this depth so a "
           "fast feed cannot balloon host memory.",
           "streaming"),
    # Interop variables (not DKTPU_-prefixed): written, never branched on.
    EnvVar("KERAS_BACKEND", "str", "",
           "Set (never read for branching) to `jax` before any keras import "
           "so the Keras-3 adapter runs on the JAX backend.",
           "interop"),
    EnvVar("KERAS_HOME", "str", "",
           "Written by `utils.set_keras_base_directory` (reference-parity "
           "shim) to point Keras-3's home at `<path>/.keras`.",
           "interop"),
)

_FALSE_STRINGS = frozenset({"0", "false", "no", "off"})


def _entry(name: str, kind: str) -> EnvVar:
    var = _registered(name)
    if var.kind != kind:
        raise TypeError(
            f"{name} is registered as kind={var.kind!r}; read it with "
            f"env_{var.kind}()")
    return var


def _raw(name: str) -> str:
    return os.environ.get(name, "").strip()


def env_bool(name: str) -> bool:
    """Registered boolean: unset/empty reads the declared default; any other
    value is truthy unless it is one of ``0/false/no/off``."""
    var = _entry(name, "bool")
    raw = _raw(name)
    if not raw:
        return bool(var.default)
    return raw.lower() not in _FALSE_STRINGS


def env_int(name: str) -> int:
    var = _entry(name, "int")
    raw = _raw(name)
    return int(raw) if raw else int(var.default)


def env_float(name: str) -> Optional[float]:
    """Registered float; a ``None`` default means "unset reads as None"
    (used for opt-in thresholds like ``DKTPU_DIVERGENCE_RESET``)."""
    var = _entry(name, "float")
    raw = _raw(name)
    if raw:
        return float(raw)
    return None if var.default is None else float(var.default)


def env_str(name: str) -> str:
    var = _entry(name, "str")
    return os.environ.get(name, "").strip() or str(var.default)


def _registered(name: str) -> EnvVar:
    """Registry row for ``name`` regardless of kind (write accessors)."""
    var = ENV_REGISTRY.get(name)
    if var is None:
        raise KeyError(
            f"{name!r} is not a registered environment variable; declare it "
            "in distkeras_tpu.runtime.config.ENV_REGISTRY (dk-check DK302)")
    return var


def env_is_set(name: str) -> bool:
    """Whether a registered variable was EXPLICITLY set (even to its
    default value) — for callers whose own defaulting must yield to an
    operator's explicit choice (e.g. the autotuner never overrides a
    hand-set knob)."""
    _registered(name)
    return name in os.environ


def env_set(name: str, value: str) -> None:
    """Write a registered variable (interop shims only)."""
    _registered(name)
    os.environ[name] = value


def env_setdefault(name: str, value: str) -> str:
    _registered(name)
    return os.environ.setdefault(name, value)


# -- docs generation --------------------------------------------------------

def iter_env_vars(category: Optional[str] = None):
    for var in ENV_REGISTRY.values():
        if category is None or var.category == category:
            yield var


def render_env_table(category: Optional[str] = None) -> str:
    """The markdown env-var table for ``category`` (None = all, with a
    category column). Injected between ``<!-- dk-env:begin ... -->`` /
    ``<!-- dk-env:end -->`` markers by ``--write-env-docs``; DK303 fails CI
    when a docs table no longer matches this rendering."""
    rows = list(iter_env_vars(category))
    with_cat = category is None
    head = "| Variable | Type | Default | Description |"
    sep = "|---|---|---|---|"
    if with_cat:
        head = "| Variable | Type | Default | Category | Description |"
        sep = "|---|---|---|---|---|"
    out = [head, sep]
    for v in rows:
        default = "unset" if v.default in (None, "") else f"`{v.default}`"
        cells = [f"`{v.name}`", v.kind, default]
        if with_cat:
            cells.append(v.category)
        cells.append(v.doc)
        out.append("| " + " | ".join(cells) + " |")
    return "\n".join(out)


def splice_env_docs(text: str, path_hint: str = "") -> str:
    """Replace every ``<!-- dk-env:begin [category=X] -->`` ...
    ``<!-- dk-env:end -->`` block in ``text`` with the freshly rendered
    table for that category."""
    import re

    def sub(m) -> str:
        category = m.group("cat") or None
        return (m.group("open") + "\n" + render_env_table(category)
                + "\n" + m.group("close"))

    pat = re.compile(
        r"(?P<open><!-- dk-env:begin(?: category=(?P<cat>[\w-]+))? -->)"
        r".*?(?P<close><!-- dk-env:end -->)",
        re.DOTALL)
    out, n = pat.subn(sub, text)
    if n == 0 and path_hint:
        raise ValueError(f"no dk-env marker block found in {path_hint}")
    return out
