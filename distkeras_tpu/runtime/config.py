"""Run-level configuration.

The reference keeps every hyperparameter as a trainer ``__init__`` kwarg
(``distkeras/trainers.py``: ``num_workers``, ``batch_size``, ``num_epoch``,
``communication_window``, ``learning_rate``, ``master_port``...). The trainers keep
that kwargs-first surface and normalize into this frozen dataclass
(``Trainer.config``); the kwarg names remain live as properties delegating here.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

_DTYPES = {None: None, "float32": jnp.float32, "bfloat16": jnp.bfloat16,
           "float16": jnp.float16}


@dataclasses.dataclass(frozen=True)
class RunConfig:
    batch_size: int = 32
    num_epoch: int = 1
    communication_window: int = 5
    learning_rate: float = 0.01
    num_workers: Optional[int] = None  # None -> all devices
    compute_dtype: Optional[str] = None  # "bfloat16" is MXU-native; params stay f32
    seed: int = 0
    shuffle: bool = False
    drop_remainder: bool = True

    @property
    def dtype(self):
        return _DTYPES[self.compute_dtype]

    def replace(self, **kw) -> "RunConfig":
        return dataclasses.replace(self, **kw)
