"""Distributed inference — parity with ``distkeras/predictors.py``.

The reference maps a deserialized model's ``predict`` over Spark partitions and
appends a prediction column (``ModelPredictor.predict(df)``, SURVEY.md §3.5). Here the
batch axis is sharded over the ``data`` mesh axis and the forward pass is one jitted
program per chunk; rows are padded to a fixed chunk size so every chunk hits the same
compiled executable (no shape-polymorphic recompiles).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.data.dataframe import DataFrame
from distkeras_tpu.models.base import Model
from distkeras_tpu.runtime.mesh import DATA_AXIS, data_mesh


class Predictor:
    """Base: ``predict(df) -> df`` with a new output column."""

    def predict(self, dataframe: DataFrame) -> DataFrame:
        raise NotImplementedError


class ModelPredictor(Predictor):
    """Append ``output_col`` with the model's raw outputs (logits).

    Parity: reference ``ModelPredictor(keras_model, features_col, output_col)``.
    ``chunk_size`` is the per-program global batch; rows are padded up then trimmed.
    """

    def __init__(
        self,
        model: Model,
        features_col: str = "features",
        output_col: str = "prediction",
        chunk_size: int = 1024,
        num_workers: Optional[int] = None,
    ):
        self.model = model
        self.features_col = features_col
        self.output_col = output_col
        self.num_workers = num_workers
        self.mesh = data_mesh(num_workers=num_workers)
        W = self.mesh.shape[DATA_AXIS]
        self.chunk_size = max(chunk_size // W, 1) * W  # divisible by worker count
        self._fwd = jax.jit(
            lambda params, x: self.model.module.apply({"params": params}, x, train=False)
        )
        rep = NamedSharding(self.mesh, P())
        self._params = jax.device_put(self.model.params, rep)
        self._shard = NamedSharding(self.mesh, P(DATA_AXIS))

    def predict(self, dataframe: DataFrame) -> DataFrame:
        x = np.asarray(dataframe[self.features_col])
        n = len(x)
        outs = []
        for start in range(0, n, self.chunk_size):
            chunk = x[start : start + self.chunk_size]
            pad = self.chunk_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
            xb = jax.device_put(jnp.asarray(chunk), self._shard)
            out = np.asarray(self._fwd(self._params, xb))
            outs.append(out[: len(out) - pad] if pad else out)
        return dataframe.with_column(self.output_col, np.concatenate(outs, axis=0))


class ProbabilityPredictor(ModelPredictor):
    """Like ModelPredictor but appends softmax probabilities."""

    def predict(self, dataframe: DataFrame) -> DataFrame:
        df = super().predict(dataframe)
        probs = jax.nn.softmax(jnp.asarray(df[self.output_col]), axis=-1)
        return df.with_column(self.output_col, np.asarray(probs))


class ClassPredictor(ModelPredictor):
    """Appends the argmax class index (the notebooks' common final step)."""

    def predict(self, dataframe: DataFrame) -> DataFrame:
        df = super().predict(dataframe)
        cls = np.asarray(df[self.output_col]).argmax(axis=-1).astype(np.int32)
        return df.with_column(self.output_col, cls)
