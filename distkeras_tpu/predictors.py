"""Distributed inference — parity with ``distkeras/predictors.py``.

The reference maps a deserialized model's ``predict`` over Spark partitions and
appends a prediction column (``ModelPredictor.predict(df)``, SURVEY.md §3.5). Here the
batch axis is sharded over the ``data`` mesh axis and the forward pass is one jitted
program per chunk; rows are padded to a fixed chunk size so every chunk hits the same
compiled executable (no shape-polymorphic recompiles).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.data.dataframe import DataFrame
from distkeras_tpu.models.base import Model
from distkeras_tpu.runtime.mesh import DATA_AXIS, data_mesh, put_global


class Predictor:
    """Base: ``predict(df) -> df`` with a new output column."""

    def predict(self, dataframe: DataFrame) -> DataFrame:
        raise NotImplementedError


def _observe_shards(stream):
    """Pass-through over a per-shard prediction stream that records per-shard
    rows and wall time — the skew between shards (max/mean of either
    histogram) is what the report surfaces for sharded inference.

    Timed around the generator resume only: the consumer's work after each
    yield (the per-shard ``np.save``) must not bleed into the NEXT shard's
    observation, or a slow filesystem write on shard s would point skew
    triage at shard s+1."""
    import time as _time

    from distkeras_tpu import telemetry

    tele = telemetry.get()
    rows = tele.histogram("predict.shard_rows")
    secs = tele.histogram("predict.shard_seconds")
    it = iter(stream)
    while True:
        t0 = _time.perf_counter()
        try:
            out = next(it)
        except StopIteration:
            return
        secs.observe(_time.perf_counter() - t0)
        rows.observe(float(len(out)))
        yield out


def _unlink_column_files(path: str, physical: str, num_shards: int) -> int:
    """Best-effort removal of a superseded physical column's shard files;
    returns how many files were actually removed.

    Missing files are fine (another process's disk, or already cleaned);
    memmapped readers holding the old manifest survive the unlink (POSIX)."""
    import os

    from distkeras_tpu.data.shards import _shard_file

    removed = 0
    for s in range(num_shards):
        try:
            os.remove(os.path.join(path, _shard_file(s, physical)))
            removed += 1
        except OSError:
            pass
    return removed


def _publish_manifest(path: str, manifest: dict, tag: str = "") -> None:
    """Atomic manifest publish (tmp + rename), the ONE write path shared by
    both predict publishes and :func:`vacuum` — ``tag`` disambiguates the
    tmp name per process on multi-host stores."""
    import json
    import os

    tmp = os.path.join(path, f".manifest.json{tag}.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(path, "manifest.json"))


def _rotate_garbage(manifest: dict, old_physical: Optional[str]) -> None:
    """Install this publish's superseded physical column as the manifest's
    ``garbage`` list (replacing the previous publish's, which the caller
    reclaims) — the single definition of the deferred-deletion rotation."""
    if old_physical is not None:
        manifest["garbage"] = [old_physical]
    else:
        manifest.pop("garbage", None)


def vacuum(path: str) -> int:
    """Reclaim superseded prediction columns' shard files NOW.

    Re-predicting an existing column writes a fresh versioned physical
    column and records the old one under the manifest's ``garbage`` list
    instead of deleting it (the **reader contract** below). Garbage is
    normally reclaimed by the NEXT predict run over the same store; call
    this to reclaim immediately — e.g. between a re-predict and a
    long-running read-only phase. Returns the number of files removed.

    Reader contract (see :meth:`ModelPredictor._predict_sharded`): a reader
    that opened the store before a re-predict may keep reading its column
    files for as long as it holds that manifest — deletion is deferred to
    the next predict run or an explicit ``vacuum()``, both of which the
    operator schedules, so "no readers predating the previous publish" is
    a deployment invariant, not a race."""
    from distkeras_tpu.data.shards import ShardStore

    store = ShardStore.open(path)
    garbage = list(store.manifest.get("garbage", []))
    removed = 0
    for physical in garbage:
        removed += _unlink_column_files(path, physical, store.num_shards)
    if garbage:
        manifest = dict(store.manifest)
        _rotate_garbage(manifest, None)
        _publish_manifest(path, manifest)
    return removed


class ModelPredictor(Predictor):
    """Append ``output_col`` with the model's raw outputs (logits).

    Parity: reference ``ModelPredictor(keras_model, features_col, output_col)``.
    ``chunk_size`` is the per-program global batch; rows are padded up then trimmed.
    """

    def __init__(
        self,
        model: Model,
        features_col: str = "features",
        output_col: str = "prediction",
        chunk_size: int = 1024,
        num_workers: Optional[int] = None,
        devices=None,
        normalize_uint8: Optional[bool] = None,
    ):
        self.model = model
        self.features_col = features_col
        self.output_col = output_col
        self.num_workers = num_workers
        #: uint8 /255 rule: default from the model (training and inference
        #: must agree on a store's normalization); the kwarg overrides.
        self.normalize_uint8 = (getattr(model, "normalize_uint8", True)
                                if normalize_uint8 is None
                                else bool(normalize_uint8))
        # ``devices``: restrict the forward mesh (the multi-process sharded
        # path passes jax.local_devices() for a collective-free per-host
        # forward). Default: every addressable device.
        self.mesh = data_mesh(num_workers=num_workers, devices=devices)
        W = self.mesh.shape[DATA_AXIS]
        self.chunk_size = max(chunk_size // W, 1) * W  # divisible by worker count
        rep = NamedSharding(self.mesh, P())
        # out_shardings=replicated: the gathered predictions are fully
        # addressable on every process (multi-host predict works; one small
        # all-gather per chunk otherwise fused away single-process).
        state = self.model.state or {}
        from distkeras_tpu.models.base import normalize_features

        norm = self.normalize_uint8
        self._fwd = jax.jit(
            lambda params, state, x: self.model.module.apply(
                {"params": params, **state}, normalize_features(x, norm),
                train=False),
            out_shardings=rep,
        )
        self._params = put_global(self.model.params, rep)
        self._state = put_global(state, rep)
        self._shard = NamedSharding(self.mesh, P(DATA_AXIS))
        self._empty_block_cache: Optional[np.ndarray] = None

    def _empty_block(self, feature_hint: Optional[np.ndarray] = None) -> np.ndarray:
        """Zero-row block with this predictor's exact output tail shape/dtype.

        Derived abstractly (``jax.eval_shape`` on the forward, then the
        subclass ``_postprocess`` on the zero-row array) so empty stream polls
        concatenate cleanly with real prediction blocks. The input spec comes
        from the model's build-time ``sample_spec``, or from a seen feature
        microbatch when the model was deserialized without one.
        """
        if self._empty_block_cache is None:
            spec = (self.model.sample_spec or (None,))[0]
            if spec is None and feature_hint is not None:
                spec = jax.ShapeDtypeStruct(np.shape(feature_hint),
                                            np.asarray(feature_hint).dtype)
            if spec is None:
                return np.empty((0,), np.float32)  # nothing to infer from yet
            x = jax.ShapeDtypeStruct((1,) + tuple(spec.shape[1:]), spec.dtype)
            out = jax.eval_shape(self.model.predict, x)
            self._empty_block_cache = self._postprocess(
                np.zeros((0,) + tuple(out.shape[1:]), out.dtype))
        return self._empty_block_cache

    def _postprocess(self, out: np.ndarray) -> np.ndarray:
        """Row-wise output transform hook (identity here; softmax/argmax in
        subclasses). Row-wise so batch and streaming paths agree exactly."""
        return out

    def _predict_array(self, x: np.ndarray) -> np.ndarray:
        """Model outputs for an arbitrary-length feature array, in fixed-shape
        padded chunks (every chunk hits the same compiled executable)."""
        from distkeras_tpu import telemetry

        tele = telemetry.get()
        n = len(x)
        outs = []
        for start in range(0, n, self.chunk_size):
            chunk = x[start : start + self.chunk_size]
            pad = self.chunk_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
            # Per-chunk batch latency (stage + forward + fetch: np.asarray
            # fences the program, so the span is the true end-to-end cost).
            with tele.span("predict.chunk"):
                xb = put_global(np.asarray(chunk), self._shard)
                out = np.asarray(self._fwd(self._params, self._state, xb))
            outs.append(out[: len(out) - pad] if pad else out)
        tele.counter("predict.rows").add(float(n))
        if n:
            tele.counter("predict.padded_rows").add(
                float(-n % self.chunk_size))
        return self._postprocess(np.concatenate(outs, axis=0))

    def predict(self, dataframe) -> "DataFrame":
        if getattr(dataframe, "is_sharded", False):
            return self._predict_sharded(dataframe)
        x = np.asarray(dataframe[self.features_col])
        return dataframe.with_column(self.output_col, self._predict_array(x))

    def predict_stream(self, source):
        """Yield ``predictions`` (one array per source microbatch, in order).

        ``source`` yields feature arrays shaped ``[n, ...]`` (n may vary per
        item; wrap single records as length-1 batches). Rows accumulate into
        ``chunk_size`` compute chunks across microbatch boundaries; only the
        final partial chunk is padded. This is both the streaming-inference
        surface (:class:`StreamingPredictor`) and the engine under the
        sharded-store path (shards are the microbatches)."""
        from collections import deque

        sizes: deque[int] = deque()  # rows per emitted-pending microbatch
        pending: list[np.ndarray] = []  # rows awaiting a forward pass
        ready: list[np.ndarray] = []  # predicted rows, FIFO
        feat_hint: list = [None]  # last seen microbatch WITH feature dims

        def pending_rows() -> int:
            return sum(len(r) for r in pending)

        def compute(flush: bool) -> None:
            x = np.concatenate(pending, axis=0) if pending else None
            if x is None or not len(x):
                return
            take = (len(x) // self.chunk_size) * self.chunk_size
            if flush:
                take = len(x)  # pad out the final partial chunk
            if take == 0:
                return
            ready.append(self._predict_array(x[:take]))
            pending.clear()
            if take < len(x):
                pending.append(x[take:])

        def drain():
            while sizes:
                need = sizes[0]
                if need == 0:
                    # Empty microbatch (e.g. an empty poll on a stream):
                    # emit an empty row block with the output tail shape.
                    sizes.popleft()
                    yield (ready[0][:0] if ready
                           else self._empty_block(feat_hint[0]))
                    continue
                if sum(len(r) for r in ready) < need:
                    return
                parts = []
                while need:
                    r = ready[0]
                    if len(r) <= need:
                        parts.append(ready.pop(0))
                        need -= len(parts[-1])
                    else:
                        parts.append(r[:need])
                        ready[0] = r[need:]
                        need = 0
                sizes.popleft()
                yield np.concatenate(parts, axis=0)

        from distkeras_tpu import telemetry

        tele = telemetry.get()
        pending_gauge = tele.gauge("predict.pending_rows")
        for microbatch in source:
            # Per-microbatch latency span, the streaming twin of the batch
            # path's ``predict.chunk``: ingest + any compute it triggers
            # (the emit walk stays outside — a slow CONSUMER must not read
            # as predictor latency). The inner ``predict.chunk`` spans
            # (fired by _predict_array) still time each forward pass.
            with tele.span("predict.stream_microbatch"):
                mb = np.asarray(microbatch)
                sizes.append(len(mb))
                if mb.ndim > 1:
                    # Even a zero-row block carries the feature tail (e.g.
                    # an empty shard's [0, d] column) — keep it as the spec
                    # hint for empty output blocks on spec-less models.
                    feat_hint[0] = mb
                if len(mb):  # an empty poll from a raw stream has no rows
                    pending.append(mb)
                    tele.counter("predict.stream_rows").add(float(len(mb)))
                if pending_rows() >= self.chunk_size:
                    compute(flush=False)
                # Rows buffered awaiting a forward pass: a gauge pinned
                # near chunk_size means the producer outruns the compute
                # chunking.
                pending_gauge.set(pending_rows())
            yield from drain()
        compute(flush=True)
        pending_gauge.set(pending_rows())
        yield from drain()

    def _predict_sharded(self, sdf):
        """Out-of-core inference: predictions stream to disk as a NEW column
        of the same store (bounded RAM: a shard's rows plus one compute
        chunk), returning a ShardedDataFrame that includes it — the
        reference's map-partitions-append-column, re-designed for disk.

        Rows buffer ACROSS shard boundaries so only the final partial chunk
        is ever padded — per-shard padding would multiply forward FLOPs for
        stores whose shards are smaller than ``chunk_size``."""
        import os

        import jax

        from distkeras_tpu.data.shards import (
            ShardStore, ShardedDataFrame, _shard_file)

        if jax.process_count() > 1:
            return self._predict_sharded_multiprocess(sdf)
        store = sdf.store
        if store.count() == 0:
            raise ValueError(f"store {store.path} has no rows to predict")

        # One shard in = one prediction array out (predict_stream buffers
        # rows across shard boundaries internally; only the final partial
        # chunk pads). The column's files are written under a FRESH
        # versioned physical name, and the manifest — the single source of
        # truth for which files a column reads — swaps atomically at the
        # end: a crash mid-stream leaves any pre-existing column fully
        # intact (no per-shard renames over live files, which could mix two
        # models' outputs).
        #
        # READER CONTRACT — deletion of the superseded version is DEFERRED:
        # its physical name goes on the manifest's ``garbage`` list and its
        # files stay on disk until the NEXT predict run over this store (or
        # an explicit ``predictors.vacuum(path)``). A concurrent reader
        # holding the pre-swap manifest therefore keeps every file it can
        # name — immediate unlinking raced such readers to FileNotFoundError
        # on shards they had not memmapped yet (ADVICE r5). Readers that
        # survive across TWO publishes must re-open the store.
        import uuid

        prior_garbage = list(store.manifest.get("garbage", []))

        physical = self.output_col
        old_physical = None
        if self.output_col in store.columns:
            old = store.columns[self.output_col]
            old_physical = (old.get("file", self.output_col)
                            if isinstance(old, dict) else self.output_col)
            physical = f"{self.output_col}.{uuid.uuid4().hex[:8]}"
        meta: dict = {}
        source = (chunk[self.features_col]
                  for chunk in sdf.iter_column_chunks(self.features_col))
        for s, out in enumerate(
                _observe_shards(self.predict_stream(source))):
            meta.update(dtype=str(out.dtype), shape=list(out.shape[1:]))
            np.save(os.path.join(store.path, _shard_file(s, physical)), out)

        manifest = dict(store.manifest)
        manifest["columns"] = dict(manifest["columns"])
        colspec = {"dtype": meta["dtype"], "shape": meta["shape"]}
        if physical != self.output_col:
            colspec["file"] = physical
        manifest["columns"][self.output_col] = colspec
        _rotate_garbage(manifest, old_physical)
        _publish_manifest(store.path, manifest)
        # Reclaim what the PREVIOUS publish deferred (reader contract above);
        # this run's superseded version waits for the next run or vacuum().
        for stale in prior_garbage:
            _unlink_column_files(store.path, stale, store.num_shards)
        return ShardedDataFrame(ShardStore.open(store.path),
                                num_partitions=sdf.num_partitions)

    def _shard_assignment(self, store, nproc: int, pid: int) -> list[int]:
        """Which global shards THIS process predicts — residency-aware.

        The training plane's locality contract is per-host shard residency:
        a host may hold only the shard files overlapping its own workers'
        rows (``shards.py`` module docstring). A contiguous index-range
        split would ask hosts for shards they don't hold and die on
        FileNotFoundError. Instead each process reports which feature
        shards are present on ITS disk, the bitmaps are all-gathered, and:

        * every process holds everything (shared filesystem) -> balanced
          contiguous ranges (the throughput-optimal split);
        * disjoint/partial residency -> shard ``s`` goes to its
          ``s % n_holders``-th holder (deterministic from the gathered
          bitmap, no extra coordination; round-robin so mirrored-but-
          incomplete disks still split the work instead of piling every
          shared shard on the lowest pid);
        * a shard nobody holds -> a contract error naming the missing
          shards, not a FileNotFoundError mid-stream.
        """
        import os

        from jax.experimental import multihost_utils

        from distkeras_tpu.data.shards import _shard_file

        S = store.num_shards
        fcol = store.columns.get(self.features_col, {})
        physical_feat = (fcol.get("file", self.features_col)
                         if isinstance(fcol, dict) else self.features_col)
        present = np.array(
            [os.path.exists(os.path.join(
                store.path, _shard_file(s, physical_feat)))
             for s in range(S)], dtype=np.int32)
        held = np.asarray(multihost_utils.process_allgather(present))
        held = held.reshape(nproc, S)
        if held.all():  # shared FS: balanced contiguous split
            return list(range(pid * S // nproc, (pid + 1) * S // nproc))
        orphans = np.flatnonzero(held.sum(axis=0) == 0)
        if orphans.size:
            raise ValueError(
                f"sharded predict residency contract violated: feature "
                f"shards {orphans.tolist()} of column "
                f"{self.features_col!r} are present on NO process's disk. "
                "Multi-process predict runs where the data lives — every "
                "shard must be held by at least one process (or use a "
                "shared filesystem).")
        mine = []
        for s in range(S):
            holders = np.flatnonzero(held[:, s])
            if holders[s % len(holders)] == pid:
                mine.append(s)
        return mine

    def _predict_sharded_multiprocess(self, sdf):
        """Multi-host out-of-core inference (the reference's map-partitions
        predict was inherently multi-executor, SURVEY.md §3.5).

        Each process takes a disjoint set of shards — the shards its own
        disk holds (:meth:`_shard_assignment`; balanced contiguous ranges on
        a shared filesystem) — and runs a PROCESS-LOCAL forward over its own
        devices: no collective in the per-chunk program, so mismatched
        per-host chunk counts cannot deadlock. Output shard files keep the
        global shard ids (1:1 with the feature shards a process read, so
        predictions land beside their features — same host). The column
        spec is derived abstractly (``_empty_block``: eval_shape +
        postprocess), so every process — including one that owned zero
        shards — computes the identical manifest and commits it atomically
        after a global barrier (per-process tmp + rename, the
        checkpoint-meta-sidecar pattern: valid on a shared filesystem AND on
        per-host local disks). Re-predicting an existing column writes a
        fresh versioned physical column; the superseded version is NOT
        deleted — it joins the manifest's ``garbage`` list (the reader
        contract in :meth:`_predict_sharded` / :func:`vacuum`), and what
        the PREVIOUS publish deferred is reclaimed after this publish's
        barrier, each process cleaning what its disk holds."""
        import os
        import uuid

        from jax.experimental import multihost_utils

        from distkeras_tpu.data.shards import (
            ShardStore, ShardedDataFrame, _shard_file)

        store = sdf.store
        if store.count() == 0:
            raise ValueError(f"store {store.path} has no rows to predict")
        nproc, pid = jax.process_count(), jax.process_index()
        my_shards = self._shard_assignment(store, nproc, pid)

        # Fresh versioned physical name when overwriting an existing column —
        # agreed across processes (process 0's draw is broadcast).
        physical = self.output_col
        old_physical = None
        if self.output_col in store.columns:
            old = store.columns[self.output_col]
            old_physical = (old.get("file", self.output_col)
                            if isinstance(old, dict) else self.output_col)
            tag = multihost_utils.broadcast_one_to_all(
                np.frombuffer(uuid.uuid4().bytes[:8], dtype=np.uint8))
            physical = f"{self.output_col}.{bytes(bytearray(tag)).hex()[:8]}"

        prior_garbage = list(store.manifest.get("garbage", []))
        local = type(self)(self.model, self.features_col, self.output_col,
                           chunk_size=self.chunk_size,
                           devices=jax.local_devices(),
                           normalize_uint8=self.normalize_uint8)
        source = (store.read_shard(s, self.features_col) for s in my_shards)
        for s, out in zip(my_shards,
                          _observe_shards(local.predict_stream(source))):
            np.save(os.path.join(store.path, _shard_file(s, physical)), out)

        # Deterministic column spec, independent of owning any shards.
        fshape, fdtype = store.column_spec(self.features_col)
        empty = local._empty_block(np.zeros((0,) + fshape, fdtype))
        colspec: dict = {"dtype": str(empty.dtype),
                         "shape": list(empty.shape[1:])}
        if physical != self.output_col:
            colspec["file"] = physical
        multihost_utils.sync_global_devices("dk_sharded_predict_written")
        manifest = dict(store.manifest)
        manifest["columns"] = dict(manifest["columns"])
        manifest["columns"][self.output_col] = colspec
        # Every process computes the identical manifest: this publish's
        # superseded version joins ``garbage`` (deferred deletion — the
        # reader contract), the previous publish's garbage leaves it.
        _rotate_garbage(manifest, old_physical)
        _publish_manifest(store.path, manifest, tag=f".p{pid}")
        multihost_utils.sync_global_devices("dk_sharded_predict_published")
        # The new manifest is live everywhere: reclaim what the PREVIOUS
        # publish deferred. Each process cleans what its disk holds.
        for stale in prior_garbage:
            _unlink_column_files(store.path, stale, store.num_shards)
        return ShardedDataFrame(ShardStore.open(store.path),
                                num_partitions=sdf.num_partitions)


class ProbabilityPredictor(ModelPredictor):
    """Like ModelPredictor but appends softmax probabilities."""

    def _postprocess(self, out: np.ndarray) -> np.ndarray:
        return np.asarray(jax.nn.softmax(jnp.asarray(out), axis=-1))


class ClassPredictor(ModelPredictor):
    """Appends the argmax class index (the notebooks' common final step)."""

    def _postprocess(self, out: np.ndarray) -> np.ndarray:
        return out.argmax(axis=-1).astype(np.int32)


class StreamingPredictor(ModelPredictor):
    """Continuous inference over an unbounded record stream.

    Parity: the reference ships a Kafka streaming-inference example (SURVEY.md
    §2 examples row — producer pushes feature records onto a topic, a consumer
    maps ``model.predict`` over microbatches and re-emits them with
    predictions). The TPU-native equivalent takes any iterator of feature
    microbatches — a generator over a socket, a queue drained by a consumer
    thread, a file tail — and yields one prediction array per input
    microbatch, in order (``predict_stream``; the machinery lives on
    :class:`ModelPredictor`, where the sharded-store path reuses it with
    shards as the microbatches).

    Records accumulate into ``chunk_size`` rows before a forward pass runs, so
    arbitrary producer batch sizes still hit one compiled fixed-shape
    executable; the final partial chunk is padded and flushed when the source
    ends. ``postprocess`` follows the subclass hook, so
    ``StreamingClassPredictor`` below emits class ids exactly like
    :class:`ClassPredictor` does for dataframes.
    """


class StreamingClassPredictor(StreamingPredictor, ClassPredictor):
    """Streaming inference emitting argmax class ids."""
