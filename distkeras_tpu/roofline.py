"""Analytic ICI scaling model for the collective-fold engines.

The north-star gate (BASELINE.md: >=90% linear scaling, CIFAR-10 CNN under
AEASGD, 1 -> 64 v5e chips) cannot be *measured* in this environment — one
real chip exists — so this module bounds it analytically from quantities that
ARE measured here:

* **compute per fold round** — the single-chip steady-state round time from
  ``bench.py`` (window x batch local steps; per-chip work is
  worker-count-invariant under the data-parallel disciplines, since each
  chip's slice stays [window, batch]);
* **collective per fold round** — every discipline's fold lowers to ONE
  fused all-reduce of the model-sized delta per round (an HLO regression
  test pins this: ``tests/test_hlo_properties.py``). Ring all-reduce moves
  ``2 x S x (N-1)/N`` bytes through each chip's link pair; v5e ICI is
  ~45 GB/s per link per direction (2D torus; one ring direction assumed —
  conservative, real meshes stripe over more links).

Efficiency is modeled with ZERO compute/communication overlap (again
conservative: XLA overlaps the fold with the tail of the local window).
The model's honest domain is the shape of the scaling curve, not 3-digit
precision; the test pins its inputs to the measured bench numbers so the
claim "the fold cost cannot push 64-chip scaling below 90%" is reproducible
arithmetic, not hope.
"""

from __future__ import annotations

import dataclasses

#: v5e ICI: ~45 GB/s per link per direction.
ICI_LINK_BYTES_PER_S = 45e9
#: DCN per host (v5e: ~25 GB/s NIC). Pass as ``link_bytes_per_s`` to model a
#: fold whose slowest hop crosses slices over DCN instead of riding ICI.
DCN_BYTES_PER_S = 25e9


def allreduce_seconds(model_bytes: float, n_chips: int,
                      link_bytes_per_s: float = ICI_LINK_BYTES_PER_S) -> float:
    """Ring all-reduce wall time: each chip sends+receives
    ``2 * S * (N-1)/N`` bytes over one link direction."""
    if n_chips <= 1:
        return 0.0
    return 2.0 * model_bytes * (n_chips - 1) / n_chips / link_bytes_per_s


@dataclasses.dataclass
class SyncStepScalingModel:
    """Per-STEP sync-DP scaling — BASELINE config #5's actual gate (ResNet-50
    on a v5e-256 pod, scaling efficiency 1 -> 256 chips).

    Unlike the window-K fold (:class:`FoldScalingModel`), synchronous DP
    all-reduces the full f32 gradient EVERY optimizer step — no window
    amortization — so the ratio is much harsher: ~100 MB of ResNet-50 grads
    against one step's compute. Past ``chips_per_slice`` the reduction goes
    hierarchical (multislice): intra-slice reduce-scatter over ICI leaves
    each chip ``grad_bytes/intra`` of reduced shards, the cross-slice
    exchange rides each HOST's DCN NIC (which carries its
    ``chips_per_host`` chips' shares), then the intra-slice all-gather
    completes — the standard v5e multislice pattern, with zero
    compute/comm overlap assumed throughout (conservative).

    Levers the model exposes (both are real knobs in this repo):

    * ``grad_bytes``: bf16 gradient all-reduce halves it
      (``ops/precision.py`` casts; psum in bf16);
    * ``grad_accum``: A micro-batches per optimizer step multiply the
      compute a single all-reduce amortizes (``Trainer(grad_accum=A)``).
    """

    step_seconds: float  # measured single-chip optimizer-step time
    grad_bytes: float  # bytes all-reduced per step (f32 grads = 4 x params)
    ici_bytes_per_s: float = ICI_LINK_BYTES_PER_S
    dcn_bytes_per_s: float = DCN_BYTES_PER_S
    chips_per_slice: int = 256  # ICI domain; beyond it the hop crosses DCN
    chips_per_host: int = 8  # v5e: 8 chips share one NIC
    grad_accum: int = 1

    def comm_seconds(self, n_chips: int) -> float:
        intra = min(n_chips, self.chips_per_slice)
        t = allreduce_seconds(self.grad_bytes, intra, self.ici_bytes_per_s)
        if n_chips > self.chips_per_slice:
            slices = -(-n_chips // self.chips_per_slice)  # ceil
            per_host = self.grad_bytes / intra * self.chips_per_host
            t += (2.0 * per_host * (slices - 1) / slices
                  / self.dcn_bytes_per_s)
        return t

    def efficiency(self, n_chips: int) -> float:
        compute = self.step_seconds * self.grad_accum
        return compute / (compute + self.comm_seconds(n_chips))

    def curve(self, chips=(1, 2, 4, 8, 16, 32, 64, 128, 256)) -> list[dict]:
        return [{"num_chips": n,
                 "comm_ms": round(self.comm_seconds(n) * 1e3, 4),
                 "efficiency": round(self.efficiency(n), 4)}
                for n in chips]


@dataclasses.dataclass
class FoldScalingModel:
    """Scaling of a window-K collective-fold discipline (AEASGD/ADAG/...).

    ``round_seconds``: measured single-chip fold-round time (compute).
    ``model_bytes``: bytes all-reduced per round (f32 delta = 4 x params).
    """

    round_seconds: float
    model_bytes: float
    link_bytes_per_s: float = ICI_LINK_BYTES_PER_S

    def comm_seconds(self, n_chips: int) -> float:
        return allreduce_seconds(self.model_bytes, n_chips,
                                 self.link_bytes_per_s)

    def efficiency(self, n_chips: int) -> float:
        """Predicted scaling efficiency: throughput(N) / (N x throughput(1)),
        assuming zero overlap of fold and local window."""
        return self.round_seconds / (self.round_seconds
                                     + self.comm_seconds(n_chips))

    def curve(self, chips=(1, 2, 4, 8, 16, 32, 64, 128, 256)) -> list[dict]:
        return [{"num_chips": n,
                 "comm_ms": round(self.comm_seconds(n) * 1e3, 4),
                 "efficiency": round(self.efficiency(n), 4)}
                for n in chips]
