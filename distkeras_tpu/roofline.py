"""Analytic ICI scaling model for the collective-fold engines.

The north-star gate (BASELINE.md: >=90% linear scaling, CIFAR-10 CNN under
AEASGD, 1 -> 64 v5e chips) cannot be *measured* in this environment — one
real chip exists — so this module bounds it analytically from quantities that
ARE measured here:

* **compute per fold round** — the single-chip steady-state round time from
  ``bench.py`` (window x batch local steps; per-chip work is
  worker-count-invariant under the data-parallel disciplines, since each
  chip's slice stays [window, batch]);
* **collective per fold round** — every discipline's fold lowers to ONE
  fused all-reduce of the model-sized delta per round (an HLO regression
  test pins this: ``tests/test_hlo_properties.py``). Ring all-reduce moves
  ``2 x S x (N-1)/N`` bytes through each chip's link pair; v5e ICI is
  ~45 GB/s per link per direction (2D torus; one ring direction assumed —
  conservative, real meshes stripe over more links).

Efficiency is modeled with ZERO compute/communication overlap (again
conservative: XLA overlaps the fold with the tail of the local window).
The model's honest domain is the shape of the scaling curve, not 3-digit
precision; the test pins its inputs to the measured bench numbers so the
claim "the fold cost cannot push 64-chip scaling below 90%" is reproducible
arithmetic, not hope.
"""

from __future__ import annotations

import dataclasses

#: v5e ICI: ~45 GB/s per link per direction.
ICI_LINK_BYTES_PER_S = 45e9
#: DCN per host (v5e: ~25 GB/s NIC). Pass as ``link_bytes_per_s`` to model a
#: fold whose slowest hop crosses slices over DCN instead of riding ICI.
DCN_BYTES_PER_S = 25e9


def allreduce_seconds(model_bytes: float, n_chips: int,
                      link_bytes_per_s: float = ICI_LINK_BYTES_PER_S) -> float:
    """Ring all-reduce wall time: each chip sends+receives
    ``2 * S * (N-1)/N`` bytes over one link direction."""
    if n_chips <= 1:
        return 0.0
    return 2.0 * model_bytes * (n_chips - 1) / n_chips / link_bytes_per_s


@dataclasses.dataclass
class FoldScalingModel:
    """Scaling of a window-K collective-fold discipline (AEASGD/ADAG/...).

    ``round_seconds``: measured single-chip fold-round time (compute).
    ``model_bytes``: bytes all-reduced per round (f32 delta = 4 x params).
    """

    round_seconds: float
    model_bytes: float
    link_bytes_per_s: float = ICI_LINK_BYTES_PER_S

    def comm_seconds(self, n_chips: int) -> float:
        return allreduce_seconds(self.model_bytes, n_chips,
                                 self.link_bytes_per_s)

    def efficiency(self, n_chips: int) -> float:
        """Predicted scaling efficiency: throughput(N) / (N x throughput(1)),
        assuming zero overlap of fold and local window."""
        return self.round_seconds / (self.round_seconds
                                     + self.comm_seconds(n_chips))

    def curve(self, chips=(1, 2, 4, 8, 16, 32, 64, 128, 256)) -> list[dict]:
        return [{"num_chips": n,
                 "comm_ms": round(self.comm_seconds(n) * 1e3, 4),
                 "efficiency": round(self.efficiency(n), 4)}
                for n in chips]
