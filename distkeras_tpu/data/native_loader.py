"""ctypes binding for the native (C++) data-plane kernels.

Builds ``distkeras_tpu/native/loader.cc`` with the system g++ on first use and
caches the shared object next to the source. Every entry point degrades to a
numpy fallback when the toolchain or the .so is unavailable, so the framework
never *requires* the native path — it's a throughput upgrade, not a dependency
(mirroring how the reference leaned on the Spark JVM without owning it).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from distkeras_tpu.runtime import config

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_SRC = os.path.join(_NATIVE_DIR, "loader.cc")
_SO = os.path.join(_NATIVE_DIR, "_loader.so")

_lib = None
_lock = threading.Lock()
_DISABLED = config.env_bool("DKTPU_NO_NATIVE")

# Must match dk_abi_version() in native/loader.cc. Bump both on any signature
# change; a mismatch (stale cached .so, or .cc edited without this constant)
# disables the native path rather than calling through a wrong prototype.
_ABI_VERSION = 2


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC,
           "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib
    if _DISABLED:
        return None
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        try:
            lib.dk_abi_version.restype = ctypes.c_int
            lib.dk_abi_version.argtypes = []
            if lib.dk_abi_version() != _ABI_VERSION:
                return None
        except AttributeError:
            return None  # pre-versioned .so: refuse it
        lib.dk_gather_rows.restype = ctypes.c_int
        lib.dk_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.dk_scale_f32.restype = None
        lib.dk_scale_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_void_p, ctypes.c_int,
        ]
        _lib = lib
        return _lib


def num_threads() -> int:
    return max(1, (os.cpu_count() or 1))


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``src[idx]`` with the index array applied to axis 0.

    ``idx`` may have any shape; the result has shape ``idx.shape + src.shape[1:]``.
    Uses the native threaded gather when available, numpy fancy indexing
    otherwise (bit-identical results).
    """
    from distkeras_tpu import telemetry

    tele = telemetry.get()
    lib = get_lib()
    if lib is None or not src.flags.c_contiguous or src.dtype == object:
        # Which path served the gather matters for perf triage: a silent
        # fallback (toolchain missing, non-contiguous column) looks like a
        # data-plane regression otherwise.
        tele.counter("native.gather_fallback_calls").add(1)
        return src[idx]
    flat_idx = np.ascontiguousarray(idx.reshape(-1), np.int64)
    row_bytes = int(src.dtype.itemsize * np.prod(src.shape[1:], dtype=np.int64))
    if row_bytes == 0:
        return src[idx]
    out = np.empty((flat_idx.size,) + src.shape[1:], src.dtype)
    with tele.span("native.gather"):
        rc = lib.dk_gather_rows(
            src.ctypes.data_as(ctypes.c_void_p), src.shape[0], row_bytes,
            flat_idx.ctypes.data_as(ctypes.c_void_p), flat_idx.size,
            out.ctypes.data_as(ctypes.c_void_p), num_threads(),
        )
    if rc != 0:
        raise IndexError("gather index out of range")
    tele.counter("native.gather_calls").add(1)
    tele.counter("native.gather_bytes").add(float(out.nbytes))
    return out.reshape(idx.shape + src.shape[1:])


def scale_f32(src: np.ndarray, offset: float, scale: float,
              bias: float = 0.0) -> np.ndarray:
    """``(src - offset) * scale + bias`` for float32 arrays (threaded when native).

    ``bias`` is applied separately rather than folded into ``offset`` so that a
    huge ``scale`` (degenerate input range) can't cancel it away in float32.
    """
    lib = get_lib()
    if lib is None or src.dtype != np.float32 or not src.flags.c_contiguous:
        return (((src - np.float32(offset)) * np.float32(scale))
                + np.float32(bias)).astype(np.float32)
    out = np.empty_like(src)
    lib.dk_scale_f32(
        src.ctypes.data_as(ctypes.c_void_p), src.size,
        ctypes.c_float(offset), ctypes.c_float(scale), ctypes.c_float(bias),
        out.ctypes.data_as(ctypes.c_void_p), num_threads(),
    )
    return out
