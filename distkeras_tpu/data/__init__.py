"""Data plane: columnar DataFrame, feature transformers, sharded batching.

Replaces the reference's Spark DataFrame substrate (SURVEY.md L1): partitions become
per-chip batch shards; the Spark-ML transformer set (``distkeras/transformers.py``) is
kept name-for-name.
"""

from distkeras_tpu.data.dataframe import DataFrame  # noqa: F401
from distkeras_tpu.data.transformers import (  # noqa: F401
    DenseTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    ReshapeTransformer,
    Transformer,
)
from distkeras_tpu.data.batching import BatchPlan, make_batches  # noqa: F401
from distkeras_tpu.data.shards import (  # noqa: F401
    ShardedBatchPlan,
    ShardedDataFrame,
    ShardStore,
    ShardWriter,
    merge_manifests,
    write_shards,
)

__all__ = [
    "DataFrame",
    "ShardedDataFrame",
    "ShardStore",
    "ShardWriter",
    "merge_manifests",
    "ShardedBatchPlan",
    "write_shards",
    "Transformer",
    "LabelIndexTransformer",
    "OneHotTransformer",
    "MinMaxTransformer",
    "ReshapeTransformer",
    "DenseTransformer",
    "BatchPlan",
    "make_batches",
]
