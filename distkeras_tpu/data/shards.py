"""Sharded, out-of-core columnar store — the Spark-DataFrame role at scale.

The reference's data plane was a Spark DataFrame: *partitioned across executors
and spillable to disk*, so no single host ever had to hold the full dataset
(SURVEY.md §1 L1, external-substrate row). The in-RAM :class:`~.dataframe.DataFrame`
covers the laptop/notebook case; this module covers the pod case the reference
got from Spark — ImageNet-shaped data (BASELINE config #5: ~150 GB over 32+
hosts) that cannot obey the "every process holds the identical full host value"
contract of ``runtime/mesh.put_global``.

Design (TPU-first, no JVM):

* **On-disk layout** — plain ``.npy`` shard files per column plus a JSON
  manifest. ``.npy`` means every reader is ``np.load(mmap_mode='r')``: gathers
  touch only the pages they index, so a 100 GB column costs RAM proportional
  to the rows *read this round*, not the dataset.
* **Worker-contiguous partitioning** — worker ``w`` of ``W`` owns global rows
  ``[w·(n//W), (w+1)·(n//W))``, mirroring Spark's ``repartition(num_workers)``
  (each executor gets one contiguous partition). Shuffling permutes *within*
  a worker's partition per epoch — the reference's per-partition minibatch
  iteration, and exactly what keeps every row host-local.
* **Per-host shard residency** — a process needs only the shard files
  overlapping its own workers' row ranges. ``ShardStore`` memmaps shards
  lazily and never opens files it is not asked to read, so hosts can hold
  strictly disjoint subsets of the data directory.
* **Per-round gather** — ``ShardedBatchPlan.round_local(r, workers)`` gathers
  just the rows those workers consume in round ``r`` (native threaded gather
  when built); the engine assembles the global device batch from each
  process's local rows (``parallel/engine.stage_round``), replacing the
  replicated-host-value contract with a "each process stages what its chips
  eat" contract.
"""

from __future__ import annotations

import dataclasses
import json
import os
from collections import OrderedDict
from typing import Mapping, Optional, Sequence

import numpy as np

from distkeras_tpu.data.native_loader import gather_rows

_MANIFEST = "manifest.json"
_PART_MANIFEST = "manifest.part.json"


def _shard_file(shard: int, col: str) -> str:
    return f"shard-{shard:05d}.{col}.npy"


_MERGE_JOURNAL = ".merge.journal.json"


def merge_manifests(path: str) -> dict:
    """Splice ``part-*/`` writer outputs into one readable store.

    Run after every :class:`ShardWriter` with ``part=k`` closed (e.g. by
    process 0 behind a barrier): renames each part's shard files into the
    global shard sequence in part-id order (same-filesystem renames — no data
    is copied), validates that every part wrote the same column schema, and
    publishes the root manifest atomically. Reads from the merged store are
    byte-identical to a single writer fed the concatenated row stream with
    per-part shard boundaries.

    Crash-safe and idempotent: the full rename plan is journaled
    (``.merge.journal.json``, atomic write) BEFORE any file moves, each move
    is skip-if-already-done on replay, and the journal is removed only after
    the root manifest publishes — so re-running after a crash at ANY point
    resumes the same merge instead of restarting the shard counter over
    already-spliced files (which would silently clobber them)."""
    journal_path = os.path.join(path, _MERGE_JOURNAL)
    if os.path.exists(journal_path):
        with open(journal_path) as f:
            plan = json.load(f)  # resume an interrupted merge
    else:
        # A FRESH merge must target a fresh directory: the rename plan starts
        # at global shard 0, so a root that already holds a published store
        # (earlier merge / direct ShardWriter run) would have its shard files
        # silently clobbered. The journal only protects the CURRENT merge
        # against crashes, not against this misuse.
        existing = [f for f in os.listdir(path)
                    if f == _MANIFEST
                    or (f.startswith("shard-") and f.endswith(".npy"))]
        if existing:
            raise FileExistsError(
                f"{path} already contains a published store "
                f"({existing[0]}{' ...' if len(existing) > 1 else ''}): "
                "merging part-*/ directories here would overwrite its "
                "shard files from global id 0. Ingest parts into a fresh "
                "directory, or remove the existing store first.")
        parts = sorted(d for d in os.listdir(path)
                       if d.startswith("part-")
                       and os.path.isdir(os.path.join(path, d)))
        if not parts:
            raise FileNotFoundError(
                f"no part-*/ writer directories under {path}")
        columns: Optional[dict] = None
        shard_rows: list[int] = []
        moves: list[list] = []  # [part_dir, local_shard, global_shard]
        g = 0
        for d in parts:
            with open(os.path.join(path, d, _PART_MANIFEST)) as f:
                pm = json.load(f)
            if not pm["shard_rows"]:
                continue  # a writer that saw zero rows contributes nothing
            if columns is None:
                columns = pm["columns"]
            elif pm["columns"] != columns:
                raise ValueError(
                    f"part {d} wrote a different column schema: "
                    f"{pm['columns']} vs {columns}")
            for i, rows in enumerate(pm["shard_rows"]):
                moves.append([d, i, g])
                shard_rows.append(int(rows))
                g += 1
        if columns is None:
            raise ValueError(f"every part under {path} was empty")
        plan = {"parts": parts, "columns": columns,
                "shard_rows": shard_rows, "moves": moves}
        tmp = journal_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(plan, f)
        os.replace(tmp, journal_path)

    for d, i, g in plan["moves"]:
        for col in plan["columns"]:
            src = os.path.join(path, d, _shard_file(i, col))
            dst = os.path.join(path, _shard_file(g, col))
            if os.path.exists(src):
                os.replace(src, dst)
            elif not os.path.exists(dst):
                raise FileNotFoundError(
                    f"merge cannot resume: neither {src} nor {dst} exists")
    for d in plan["parts"]:
        pdir = os.path.join(path, d)
        try:
            os.remove(os.path.join(pdir, _PART_MANIFEST))
        except OSError:
            pass
        try:
            os.rmdir(pdir)
        except OSError:
            pass

    shard_rows = [int(r) for r in plan["shard_rows"]]
    offsets = np.concatenate([[0], np.cumsum(shard_rows)]).tolist()
    manifest = {
        "version": 1,
        "num_rows": int(offsets[-1]),
        "columns": plan["columns"],
        "shard_rows": shard_rows,
        "shard_offsets": [int(o) for o in offsets[:-1]],
    }
    tmp = os.path.join(path, ".manifest.json.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(path, _MANIFEST))
    os.remove(journal_path)
    return manifest


class ShardWriter:
    """Streaming writer: append row chunks, emit ``rows_per_shard``-row shard
    files. Nothing is ever held beyond one shard's buffer, so a 100 GB dataset
    can be written from a generator with bounded RAM (the ingest-side half of
    the out-of-core contract).

    **Distributed ingest** (the Spark-executor-parallel write): pass
    ``part=k`` on writer ``k`` of N — each writer streams its own row range
    into an isolated ``part-000NN/`` subdirectory (no cross-writer
    coordination, any filesystem), then ONE caller runs
    :func:`merge_manifests` after every writer closed, which splices the
    parts into the global shard sequence (cheap same-filesystem renames)
    and publishes the root manifest. Part order = part id, so the merged
    row order is writer 0's rows, then writer 1's, ...
    """

    def __init__(self, path: str, rows_per_shard: int,
                 part: Optional[int] = None):
        if rows_per_shard < 1:
            raise ValueError(f"rows_per_shard must be >= 1, got {rows_per_shard}")
        self.path = path
        self.part = part
        self._dir = (path if part is None
                     else os.path.join(path, f"part-{int(part):05d}"))
        self.rows_per_shard = int(rows_per_shard)
        os.makedirs(self._dir, exist_ok=True)
        self._buf: dict[str, list[np.ndarray]] = {}
        self._buffered = 0
        self._shards: list[int] = []  # rows per emitted shard
        self._meta: Optional[dict] = None
        self._closed = False

    def append(self, **columns: np.ndarray) -> None:
        cols = {k: np.asarray(v) for k, v in columns.items()}
        n = {len(v) for v in cols.values()}
        if len(n) != 1:
            raise ValueError(
                f"column length mismatch: { {k: len(v) for k, v in cols.items()} }")
        n = n.pop()
        if self._meta is None:
            self._meta = {
                k: {"dtype": str(v.dtype), "shape": list(v.shape[1:])}
                for k, v in cols.items()
            }
            self._buf = {k: [] for k in cols}
        elif set(cols) != set(self._meta):
            raise ValueError(
                f"columns changed mid-stream: {sorted(cols)} vs {sorted(self._meta)}")
        for k, v in cols.items():
            m = self._meta[k]
            if list(v.shape[1:]) != m["shape"] or str(v.dtype) != m["dtype"]:
                raise ValueError(
                    f"column {k!r}: got {v.dtype}{list(v.shape[1:])}, "
                    f"expected {m['dtype']}{m['shape']}")
            self._buf[k].append(v)
        self._buffered += n
        while self._buffered >= self.rows_per_shard:
            self._flush(self.rows_per_shard)

    def _flush(self, rows: int) -> None:
        shard = len(self._shards)
        for k, chunks in self._buf.items():
            cat = np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
            np.save(os.path.join(self._dir, _shard_file(shard, k)), cat[:rows])
            self._buf[k] = [cat[rows:]] if rows < len(cat) else []
        self._shards.append(rows)
        self._buffered -= rows

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # Only publish the manifest on clean exit: a partially-written store
        # without a manifest is unreadable (fails loudly) rather than
        # silently truncated. Tolerates an explicit close() inside the block
        # (the way to get the returned manifest).
        if exc_type is None and not self._closed:
            self.close()

    def close(self) -> dict:
        """Flush the tail shard and write the manifest; returns the manifest.

        A ``part=k`` writer publishes a PART manifest inside its own
        subdirectory instead of the root one — the store only becomes
        readable once :func:`merge_manifests` splices every part."""
        if self._closed:
            raise RuntimeError("ShardWriter already closed")
        if self._buffered:
            self._flush(self._buffered)
        self._closed = True
        offsets = np.concatenate([[0], np.cumsum(self._shards)]).tolist()
        manifest = {
            "version": 1,
            "num_rows": int(offsets[-1]),
            "columns": self._meta or {},
            "shard_rows": [int(r) for r in self._shards],
            "shard_offsets": [int(o) for o in offsets[:-1]],
        }
        name = _MANIFEST if self.part is None else _PART_MANIFEST
        with open(os.path.join(self._dir, name), "w") as f:
            json.dump(manifest, f)
        return manifest


def write_shards(path: str, columns: Mapping[str, np.ndarray],
                 rows_per_shard: int) -> dict:
    """One-shot convenience: shard in-RAM columns to ``path``."""
    w = ShardWriter(path, rows_per_shard)
    w.append(**dict(columns))
    return w.close()


class ShardStore:
    """Reader over a shard directory: lazily memmapped, locality-honest.

    ``gather(col, row_ids)`` opens only the shard files the ids land in — a
    host holding a disjoint subset of the shards can serve every row it owns
    and fails loudly (FileNotFoundError) on rows it does not, which is the
    property the per-host data plane relies on (and tests assert)."""

    #: open-memmap cap. Each memmap holds a file descriptor for its lifetime;
    #: a ~150 GB store can span thousands of shard files, and an unbounded
    #: cache would blow the default 1024-fd ulimit mid-epoch. LRU keeps the
    #: hot working set (a round touches few shards) while bounding fds.
    MAX_OPEN_MAPS = 128

    def __init__(self, path: str, max_open_maps: Optional[int] = None):
        self.path = path
        with open(os.path.join(path, _MANIFEST)) as f:
            m = json.load(f)
        self.manifest = m
        self.num_rows: int = m["num_rows"]
        self.columns: dict = m["columns"]
        self._offsets = np.asarray(m["shard_offsets"] + [m["num_rows"]], np.int64)
        self._max_open = max_open_maps or self.MAX_OPEN_MAPS
        self._maps: "OrderedDict[tuple[int, str], np.ndarray]" = OrderedDict()

    @classmethod
    def open(cls, path: str) -> "ShardStore":
        return cls(path)

    def count(self) -> int:
        return self.num_rows

    def column_spec(self, col: str) -> tuple[tuple, np.dtype]:
        c = self.columns[col]
        return tuple(c["shape"]), np.dtype(c["dtype"])

    def shard_range(self, shard: int) -> tuple[int, int]:
        return int(self._offsets[shard]), int(self._offsets[shard + 1])

    @property
    def num_shards(self) -> int:
        return len(self._offsets) - 1

    def shards_for_rows(self, lo: int, hi: int) -> list[int]:
        """Shard ids overlapping global row range ``[lo, hi)``."""
        s0 = int(np.searchsorted(self._offsets, lo, side="right")) - 1
        s1 = int(np.searchsorted(self._offsets, hi, side="left"))
        return list(range(max(s0, 0), max(s1, 0)))

    def _map(self, shard: int, col: str) -> np.ndarray:
        # Columns may be stored under a versioned physical name ("file"):
        # re-predicting an existing output column writes fresh files and
        # swaps the manifest atomically instead of renaming over live ones.
        phys = self.columns.get(col, {}).get("file", col)
        key = (shard, col)
        mm = self._maps.get(key)
        if mm is None:
            fp = os.path.join(self.path, _shard_file(shard, phys))
            mm = np.load(fp, mmap_mode="r")
            while len(self._maps) >= self._max_open:
                # Dropping the reference closes the underlying mmap + fd
                # (gathers copy out of the map, so no views outlive it).
                self._maps.popitem(last=False)
            self._maps[key] = mm
        else:
            self._maps.move_to_end(key)
        return mm

    def close(self) -> None:
        """Release every cached memmap (and its file descriptor)."""
        self._maps.clear()

    def read_shard(self, shard: int, col: str) -> np.ndarray:
        """One whole shard of a column (a single sequential read — the fast
        path for full scans). Returns a writable COPY: handing out the
        cached memmap would let consumers pin evicted maps' file
        descriptors past the LRU bound."""
        return np.array(self._map(shard, col))

    def gather(self, col: str, row_ids: np.ndarray) -> np.ndarray:
        """``rows[row_ids]`` across shard files; result shape
        ``row_ids.shape + row_shape``. Order-preserving."""
        ids = np.asarray(row_ids)
        flat = ids.reshape(-1).astype(np.int64)
        if flat.size and (flat.min() < 0 or flat.max() >= self.num_rows):
            raise IndexError(
                f"row ids out of range [0, {self.num_rows}) for column {col!r}")
        shape, dtype = self.column_spec(col)
        out = np.empty((flat.size,) + shape, dtype)
        shard_of = np.searchsorted(self._offsets, flat, side="right") - 1
        for s in np.unique(shard_of):
            sel = np.nonzero(shard_of == s)[0]
            base = self._offsets[s]
            # memmap-backed: the gather faults in only the touched pages.
            out[sel] = gather_rows(self._map(int(s), col), flat[sel] - base)
        return out.reshape(ids.shape + shape)


class ShardedDataFrame:
    """Trainer-facing handle over a :class:`ShardStore` — the drop-in for
    ``Trainer.train(dataframe)`` at out-of-core scale. Row data stays on disk;
    only per-round gathers materialize. Column transforms belong at ingest
    time (``ShardWriter``), like Spark pipelines ran before ``repartition``."""

    is_sharded = True

    def __init__(self, store_or_path, num_partitions: Optional[int] = None):
        self.store = (store_or_path if isinstance(store_or_path, ShardStore)
                      else ShardStore.open(store_or_path))
        self.num_partitions = num_partitions

    @property
    def columns(self) -> list[str]:
        return list(self.store.columns)

    def count(self) -> int:
        return self.store.count()

    def __len__(self) -> int:
        return self.store.count()

    def __contains__(self, name: str) -> bool:
        return name in self.store.columns

    def repartition(self, n: int) -> "ShardedDataFrame":
        return ShardedDataFrame(self.store, num_partitions=n)

    def iter_column_chunks(self, *cols: str):
        """Yield ``{col: rows}`` one shard at a time — the bounded-memory
        row stream that out-of-core predictors/evaluators consume (the
        Spark-partition-iterator analogue). Whole-shard reads go straight to
        the memmap (one sequential read; no per-row index math)."""
        for s in range(self.store.num_shards):
            yield {c: self.store.read_shard(s, c) for c in cols}

    def __getattr__(self, name):
        if name in {"with_column", "select", "drop", "take_rows", "shuffle",
                    "split", "random_split", "randomSplit", "iter_rows"}:
            raise AttributeError(
                f"ShardedDataFrame does not materialize rows; {name!r} is an "
                "in-RAM DataFrame op. Apply one-shot transforms at ingest "
                "time (ShardWriter), per-round transforms at training time "
                "(Trainer(transform=fn) / make_batches(transform=fn)) — "
                "shuffling is the planner's job (make_batches(..., "
                "shuffle=True) permutes within partitions).")
        raise AttributeError(name)


def worker_partition(num_rows: int, num_workers: int) -> list[tuple[int, int]]:
    """Worker ``w``'s contiguous global row range (Spark repartition analogue).

    Equal-sized ``n // W`` partitions; the remainder tail is dropped, matching
    the in-RAM planner's drop of rows that don't fill a complete round."""
    rpw = num_rows // num_workers
    return [(w * rpw, (w + 1) * rpw) for w in range(num_workers)]


def worker_major_index(
    num_rows: int,
    num_workers: int,
    window: int,
    batch_size: int,
    num_epoch: int = 1,
    shuffle: bool = False,
    seed: int = 0,
) -> np.ndarray:
    """The sharded schedule: ``[rounds, W, K, B]`` global row ids where row
    ``index[r, w]`` ⊂ worker ``w``'s contiguous partition for every round.

    Deterministic in ``seed`` — every process computes the identical matrix,
    which is what lets hosts stage disjoint data without coordination. With
    ``shuffle``, each (epoch, worker) gets an independent permutation *within
    the worker's partition* (per-partition shuffling, the Spark-era
    semantics); rows beyond ``rounds_per_epoch·K·B`` differ per epoch."""
    per_worker_round = window * batch_size
    rpw = num_rows // num_workers
    if rpw < per_worker_round:
        raise ValueError(
            f"each worker's partition has {rpw} rows but one round consumes "
            f"window*batch_size = {per_worker_round}; shrink "
            "batch_size/communication_window or add data")
    rounds_per_epoch = rpw // per_worker_round
    used = num_workers * rounds_per_epoch * per_worker_round
    if used < num_rows:
        import warnings

        remainder = num_rows - rpw * num_workers
        truncated = num_rows - remainder - used
        warnings.warn(
            f"sharded plan uses {used} of {num_rows} rows per epoch "
            f"({num_rows - used} dropped: {remainder} to the worker "
            f"remainder num_rows % num_workers, {truncated} to round "
            f"truncation — each worker's {rpw}-row partition fits "
            f"{rounds_per_epoch} full rounds of window*batch_size="
            f"{per_worker_round}). With shuffle=True different rows are "
            "dropped each epoch; resize batch_size/communication_window to "
            "change the fit.",
            stacklevel=2,
        )
    rng = np.random.default_rng(seed)
    epochs = []
    for _ in range(num_epoch):
        per_w = []
        for w in range(num_workers):
            local = rng.permutation(rpw) if shuffle else np.arange(rpw)
            per_w.append(
                w * rpw
                + local[: rounds_per_epoch * per_worker_round].reshape(
                    rounds_per_epoch, window, batch_size))
        epochs.append(np.stack(per_w, axis=1))  # [rounds, W, K, B]
    return np.concatenate(epochs, axis=0)


@dataclasses.dataclass
class ShardedBatchPlan:
    """A :class:`~.batching.BatchPlan`-shaped schedule whose rows live on disk.

    Same engine-facing surface (``num_rounds``/``samples_per_round``/
    ``round``), plus the locality contract: ``is_local=True`` tells the run
    loop to stage per-process rows via :meth:`round_local` instead of the
    full-host ``round`` gather (``parallel/engine.stage_round``)."""

    store: ShardStore
    features_col: str
    label_col: str
    index: np.ndarray  # [rounds, W, K, B] global row ids
    num_workers: int
    window: int
    batch_size: int
    rows_total: int
    #: optional training-time ``fn(features, labels, rng)`` (see
    #: ``batching.apply_round_transform``): applied per worker slice with a
    #: (transform_seed, round, worker)-seeded rng, so disjoint per-host
    #: staging (round_local) and full staging (round) transform identically —
    #: the lazy Spark-pipeline half the store's ingest-time-only transforms
    #: could not express (per-epoch randomized augmentation).
    transform: object = None
    transform_seed: int = 0

    is_local = True

    @property
    def num_rounds(self) -> int:
        return self.index.shape[0]

    @property
    def rows_used(self) -> int:
        return int(self.index.size)

    @property
    def steps_per_worker(self) -> int:
        return self.num_rounds * self.window

    @property
    def samples_per_round(self) -> int:
        return self.num_workers * self.window * self.batch_size

    def round(self, r: int) -> tuple[np.ndarray, np.ndarray]:
        """Full ``[W, K, B, ...]`` gather — valid only where every shard is
        present (single host, or a shared filesystem)."""
        return self.round_local(r, range(self.num_workers))

    def round_local(self, r: int, workers: Sequence[int]
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Rows for the given workers only: ``[len(workers), K, B, ...]``.
        Touches only the shards overlapping those workers' partitions."""
        workers = list(workers)
        idx = self.index[r][np.asarray(workers, np.int64)]
        xs = self.store.gather(self.features_col, idx)
        ys = self.store.gather(self.label_col, idx)
        if self.transform is not None:
            from distkeras_tpu.data.batching import apply_round_transform

            # Seeded by GLOBAL worker id: a host staging workers [2, 3]
            # transforms them exactly as a full-store host would.
            xs, ys = apply_round_transform(
                self.transform, self.transform_seed, r, workers, xs, ys)
        return xs, ys

    def local_shards(self, workers: Sequence[int]) -> list[int]:
        """Shard ids a process hosting ``workers`` needs on local disk."""
        parts = worker_partition(self.store.count(), self.num_workers)
        shards: set[int] = set()
        for w in workers:
            lo, hi = parts[w]
            shards.update(self.store.shards_for_rows(lo, hi))
        return sorted(shards)


def make_sharded_batches(
    df,
    features_col: str,
    label_col: str,
    batch_size: int,
    num_workers: int,
    window: int = 1,
    num_epoch: int = 1,
    shuffle: bool = False,
    seed: int = 0,
    transform=None,
) -> ShardedBatchPlan:
    """Plan ``num_epoch`` passes over a :class:`ShardedDataFrame` /
    :class:`ShardStore` (the disk-backed twin of ``batching.make_batches``)."""
    store = df.store if isinstance(df, ShardedDataFrame) else df
    for col in (features_col, label_col):
        if col not in store.columns:
            raise KeyError(f"column {col!r} not in store ({list(store.columns)})")
    index = worker_major_index(
        store.count(), num_workers, window, batch_size,
        num_epoch=num_epoch, shuffle=shuffle, seed=seed)
    return ShardedBatchPlan(
        store=store,
        features_col=features_col,
        label_col=label_col,
        index=index,
        num_workers=num_workers,
        window=window,
        batch_size=batch_size,
        rows_total=store.count() * num_epoch,
        transform=transform,
        transform_seed=seed,
    )
