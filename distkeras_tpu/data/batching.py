"""Batch planning: DataFrame -> device-shaped minibatch arrays.

This is where the Spark semantics become array semantics. The reference pipeline is
``df.repartition(num_workers)`` then each executor iterates its partition in
``batch_size`` minibatches and syncs with the parameter server every
``communication_window`` steps (``workers.py`` hot loop, SURVEY.md §3.1).

Here the same schedule is planned up front as an **index matrix** — one int32 row id
per (round, worker, step, sample) — and gathered round-by-round::

    plan.round(r) -> features [num_workers, window, batch_size, ...], labels [...]

One copy of the data lives in host RAM regardless of ``num_epoch`` (the plan stores
permutations, not copies), so 90-epoch ImageNet plans cost 90 index rows, not 90
datasets. Round ``r`` = one jitted device program: every worker runs ``window`` local
steps on its ``[window, batch_size]`` slice, then the collective fold fires.
Worker-major layout keeps each worker's rows contiguous (the moral equivalent of a
Spark partition). The leading worker axis is sharded over the ``data`` mesh axis, so
each chip only ever receives its own slice.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from distkeras_tpu.data.dataframe import DataFrame


def apply_round_transform(transform, seed: int, r: int, workers, xs, ys):
    """Training-time row transform, deterministic in ``(seed, round, worker)``.

    ``transform(features[n, ...], labels[n, ...], rng) -> (features, labels)``
    is called once per worker slice with the slice flattened to rows and an
    independent ``np.random.Generator`` seeded from the triple — so
    ``round_local(r, ws)`` equals ``round(r)[ws]`` by construction, and
    disjoint multi-host staging sees exactly the rows replicated staging
    would (the property the 2-proc equality tests pin). This is the lazy
    half of the Spark pipeline the reference chained over its distributed
    DataFrame: per-epoch randomized augmentation (crop/flip) that ingest-time
    transforms cannot express. Row count must be preserved; dtype/shape of
    the rows may change (e.g. uint8 pixels -> normalized float32)."""
    out_x, out_y = [], []
    for i, w in enumerate(workers):
        rng = np.random.default_rng(
            np.random.SeedSequence((int(seed), int(r), int(w))))
        lead = xs[i].shape[:2]  # [K, B]
        n = lead[0] * lead[1]
        fx, fy = transform(xs[i].reshape((n,) + xs[i].shape[2:]),
                           ys[i].reshape((n,) + ys[i].shape[2:]), rng)
        fx, fy = np.asarray(fx), np.asarray(fy)
        if len(fx) != n or len(fy) != n:
            raise ValueError(
                f"transform must preserve row count: got {len(fx)}/{len(fy)} "
                f"rows for {n} in")
        out_x.append(fx.reshape(lead + fx.shape[1:]))
        out_y.append(fy.reshape(lead + fy.shape[1:]))
    return np.stack(out_x), np.stack(out_y)


@dataclasses.dataclass
class BatchPlan:
    x: np.ndarray  # [n, ...feature dims] — single materialized copy
    y: np.ndarray  # [n, ...label dims]
    index: np.ndarray  # [rounds, W, K, B] int64 row ids
    num_workers: int
    window: int
    batch_size: int
    rows_total: int
    #: optional training-time ``fn(features, labels, rng)`` applied to every
    #: staged round (see :func:`apply_round_transform`); seeded per
    #: (transform_seed, round, worker).
    transform: object = None
    transform_seed: int = 0

    @property
    def num_rounds(self) -> int:
        return self.index.shape[0]

    @property
    def rows_used(self) -> int:
        return int(self.index.size)

    @property
    def steps_per_worker(self) -> int:
        return self.num_rounds * self.window

    @property
    def samples_per_round(self) -> int:
        return self.num_workers * self.window * self.batch_size

    def round(self, r: int) -> tuple[np.ndarray, np.ndarray]:
        """Materialize round ``r``: ``[W, K, B, ...]`` feature + label arrays.

        Uses the native threaded gather (``data/native_loader.py``) when built;
        falls back to numpy fancy indexing bit-identically.
        """
        from distkeras_tpu.data.native_loader import gather_rows

        idx = self.index[r]
        xs, ys = gather_rows(self.x, idx), gather_rows(self.y, idx)
        if self.transform is not None:
            xs, ys = apply_round_transform(
                self.transform, self.transform_seed, r,
                range(self.num_workers), xs, ys)
        return xs, ys


def make_batches(
    df: DataFrame,
    features_col: str,
    label_col: str,
    batch_size: int,
    num_workers: int,
    window: int = 1,
    num_epoch: int = 1,
    shuffle: bool = False,
    seed: int = 0,
    transform=None,
) -> BatchPlan:
    """Lay out ``num_epoch`` passes over ``df`` as fold-round index matrices.

    Rows that don't fill a complete round are dropped (the reference likewise
    truncates trailing partial minibatches per partition). With ``shuffle`` each
    epoch gets an independent permutation, so dropped rows differ per epoch.

    ``transform``: optional training-time ``fn(features, labels, rng)`` row
    transform applied to every staged round, deterministically seeded per
    (seed, round, worker) — see :func:`apply_round_transform`.

    A :class:`~.shards.ShardedDataFrame` routes to the disk-backed planner
    (``shards.make_sharded_batches``): same trainer call, out-of-core data
    plane — rows stay on disk and each process stages only its own workers'
    rows. Memmap-backed columns in a plain DataFrame also stay on disk
    (``np.asarray`` of a memmap is a view): the single-host out-of-core case
    needs no special type.
    """
    if getattr(df, "is_sharded", False):
        from distkeras_tpu.data.shards import make_sharded_batches

        return make_sharded_batches(
            df, features_col, label_col, batch_size, num_workers,
            window=window, num_epoch=num_epoch, shuffle=shuffle, seed=seed,
            transform=transform)
    x = np.asarray(df[features_col])
    y = np.asarray(df[label_col])
    n = len(x)
    per_round = num_workers * window * batch_size
    if n < per_round:
        raise ValueError(
            f"dataset has {n} rows but one fold round needs "
            f"num_workers*window*batch_size = {per_round}; "
            "shrink batch_size/communication_window or add data"
        )

    rng = np.random.default_rng(seed)
    rounds_per_epoch = n // per_round
    epochs = []
    for _ in range(num_epoch):
        idx = rng.permutation(n) if shuffle else np.arange(n)
        epochs.append(
            idx[: rounds_per_epoch * per_round].reshape(
                rounds_per_epoch, num_workers, window, batch_size
            )
        )
    index = np.concatenate(epochs, axis=0)
    return BatchPlan(
        x=x,
        y=y,
        index=index,
        num_workers=num_workers,
        window=window,
        batch_size=batch_size,
        rows_total=n * num_epoch,
        transform=transform,
        transform_seed=seed,
    )
