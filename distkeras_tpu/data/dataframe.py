"""A minimal columnar DataFrame.

The reference leans on Spark DataFrames for everything row-shaped: training input
(``Trainer.train(dataframe)``), transformer pipelines, prediction output columns.
This is the TPU-side stand-in: named numpy columns, immutable ops, no JVM. It is a
*data-plane* object — trainers convert it to device arrays once, at batch-plan time;
nothing here is traced.

API parity notes (SURVEY.md §2, ``utils.py``):
* ``with_column`` ~ ``new_dataframe_row`` / Spark ``withColumn``
* ``repartition(n)`` ~ Spark repartition — here a metadata hint consumed by trainers
* ``shuffle()`` ~ ``utils.shuffle(dataframe)``
* ``precache()`` ~ ``utils.precache`` (force materialization) — numpy is always
  materialized, so it only validates column alignment.
"""

from __future__ import annotations

from typing import Iterator, Mapping, Optional, Sequence

import numpy as np


class DataFrame:
    def __init__(self, columns: Mapping[str, np.ndarray], num_partitions: Optional[int] = None):
        if not columns:
            raise ValueError("DataFrame needs at least one column")
        cols = {k: np.asarray(v) for k, v in columns.items()}
        n = {len(v) for v in cols.values()}
        if len(n) != 1:
            raise ValueError(f"column length mismatch: { {k: len(v) for k, v in cols.items()} }")
        self._cols = cols
        self._num_rows = n.pop()
        self.num_partitions = num_partitions

    # -- construction ------------------------------------------------------
    @classmethod
    def from_arrays(cls, **columns) -> "DataFrame":
        return cls(columns)

    # -- inspection --------------------------------------------------------
    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    def count(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def head(self, n: int = 5) -> dict[str, np.ndarray]:
        return {k: v[:n] for k, v in self._cols.items()}

    # -- transformation (all return new frames) ----------------------------
    def with_column(self, name: str, values: np.ndarray) -> "DataFrame":
        cols = dict(self._cols)
        cols[name] = np.asarray(values)
        return DataFrame(cols, self.num_partitions)

    def select(self, *names: str) -> "DataFrame":
        return DataFrame({n: self._cols[n] for n in names}, self.num_partitions)

    def drop(self, *names: str) -> "DataFrame":
        return DataFrame(
            {k: v for k, v in self._cols.items() if k not in names}, self.num_partitions
        )

    def take_rows(self, idx: np.ndarray) -> "DataFrame":
        return DataFrame({k: v[idx] for k, v in self._cols.items()}, self.num_partitions)

    def repartition(self, n: int) -> "DataFrame":
        return DataFrame(self._cols, num_partitions=n)

    def shuffle(self, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        return self.take_rows(rng.permutation(self._num_rows))

    def precache(self) -> "DataFrame":
        return self

    def split(self, fraction: float, seed: int = 0) -> tuple["DataFrame", "DataFrame"]:
        """Random train/test split (two-way shorthand for :meth:`random_split`)."""
        a, b = self.random_split([fraction, 1.0 - fraction], seed=seed)
        return a, b

    def random_split(self, weights: Sequence[float], seed: int = 0) -> list["DataFrame"]:
        """N-way random split by relative ``weights`` — Spark's
        ``DataFrame.randomSplit([0.8, 0.2])``, so reference notebooks port
        without rewriting their split calls."""
        w = np.asarray(weights, dtype=np.float64)
        if len(w) < 1 or (w <= 0).any():
            raise ValueError(f"weights must be positive, got {list(weights)}")
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self._num_rows)
        cuts = np.floor(np.cumsum(w / w.sum()) * self._num_rows).astype(int)
        return [self.take_rows(part) for part in np.split(idx, cuts[:-1])]

    #: Spark-spelled alias (the notebooks call ``df.randomSplit``).
    randomSplit = random_split

    def iter_rows(self) -> Iterator[dict]:
        for i in range(self._num_rows):
            yield {k: v[i] for k, v in self._cols.items()}
