"""Round prefetcher: overlap host-side gather + H2D transfer with device compute.

The reference got pipelining for free from Spark's executor iterators; here a
background thread materializes round ``r+depth`` (native gather) and stages it on
device (``device_put``) while the accelerator crunches round ``r``. jax dispatch is
async, so the main loop's only synchronous cost becomes a queue pop.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional, Union

from distkeras_tpu.runtime import config

#: how many per-round consumer waits :attr:`RoundFeeder.waits` retains.
#: Open-ended streams run forever; an unbounded ``list[float]`` is a slow
#: memory leak, so the tail is a deque and the *sum* is kept separately
#: (``wait_seconds``) so total-stall accounting never loses evicted entries.
WAITS_KEEP = 4096


class RoundFeeder:
    """Iterate ``(r, staged_batch)`` over a work-item source with lookahead.

    ``items`` is either an int N (the classic bounded mode: item indices
    ``start_round..N``, ``stage(r)`` receives the index) or any iterable —
    including an **unbounded** one (a live stream source): the feeder
    enumerates it and ``stage(item)`` receives each yielded item, while the
    ``r`` handed to the consumer is the item's ordinal (``start_round`` +
    position), which is also the index fault injection addresses. Epoch
    bookkeeping therefore lives entirely in the caller; this class only
    knows "next item", which is what lets the engine run loops accept a
    stream that never ends.

    ``stage(r_or_item) -> batch`` does the gather + device_put; it runs on
    the feeder thread. Exceptions propagate to the consumer on the next pop.

    Abandonment-safe: if the consumer stops iterating early (``engine.run``
    raised mid-loop, generator dropped), :meth:`close` runs from the
    generator's ``finally`` — the feeder thread is unblocked from a full
    queue, told to stop, and joined, and every staged batch still queued is
    dropped. Without this the daemon thread would sit blocked on
    ``Queue.put`` forever, pinning staged device arrays (HBM + host RAM) for
    the life of the process.

    Resilience (docs/RESILIENCE.md):

    * **Stage retry**: ``stage_retries`` (env ``DKTPU_FEEDER_RETRIES``,
      default 0 = off) retries a *failed* stage call with exponential
      backoff before propagating — transient gather errors (a flaky NFS
      read) no longer kill the run.
    * **Stall watchdog**: the consumer warns (``resilience.
      feeder_stall_warnings``) at exponentially spaced thresholds starting
      at ``stall_warn`` seconds (env ``DKTPU_FEEDER_WARN``, default 1.0)
      while blocked on an empty queue, and after ``stall_timeout`` seconds
      (env ``DKTPU_FEEDER_TIMEOUT``, default 300) declares the input
      pipeline dead with :class:`~distkeras_tpu.resilience.errors.
      FeederStalledError` — a wedged data plane fails the run (and hands
      control to the Supervisor) instead of hanging it forever.
    * **Injection**: ``stall@r:s`` / ``feeder_error@r`` faults from the
      ambient :class:`~distkeras_tpu.resilience.faults.FaultPlan` fire in
      :meth:`_stage_once` (item index = round in per-round mode, block
      index under blocked execution).
    """

    def __init__(self, items: Union[int, Iterable], stage: Callable,
                 start_round: int = 0, depth: int = 2,
                 stall_timeout: Optional[float] = None,
                 stall_warn: Optional[float] = None,
                 stage_retries: Optional[int] = None,
                 retry_backoff_s: float = 0.05):
        self.items = items
        #: bounded-mode round count (None in iterable mode).
        self.num_rounds = items if isinstance(items, int) else None
        self.stage = stage
        self.start_round = start_round
        self.depth = max(1, depth)
        self.stall_timeout = (config.env_float("DKTPU_FEEDER_TIMEOUT")
                              if stall_timeout is None else float(stall_timeout))
        self.stall_warn = (config.env_float("DKTPU_FEEDER_WARN")
                           if stall_warn is None else float(stall_warn))
        self.stage_retries = (config.env_int("DKTPU_FEEDER_RETRIES")
                              if stage_retries is None else int(stage_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        #: consumer-side seconds blocked waiting for each yielded round —
        #: the feed-overlap diagnostic. Because jax dispatch is async, the
        #: consumer loop runs ahead of the device; per-round waits beyond
        #: the warmup round mean the gather+transform+device_put pipeline
        #: is slower than the dispatch loop (staging NOT hidden). Bounded
        #: (last :data:`WAITS_KEEP` entries) so open-ended streams do not
        #: leak; :attr:`wait_seconds` keeps the exact running total the
        #: engine run loops surface as ``engine.feed_wait_seconds``.
        self.waits: collections.deque = collections.deque(maxlen=WAITS_KEEP)
        #: exact sum of EVERY recorded wait, including entries the bounded
        #: :attr:`waits` deque has already evicted.
        self.wait_seconds: float = 0.0

    def _put(self, item) -> bool:
        """Blocking put that aborts (returns False) once close() is called."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _stage_once(self, r: int, item):
        """One stage attempt, with scheduled fault injection applied first.
        ``r`` is the ordinal the fault plan indexes by; ``item`` is what the
        stage callback receives (== r in bounded mode)."""
        from distkeras_tpu.resilience import faults

        plan = faults.active_plan()
        if plan is not None:
            stall = plan.feeder_stall(r)
            if stall > 0:
                time.sleep(stall)
            if plan.feeder_error(r):
                from distkeras_tpu.resilience.errors import InjectedFault

                raise InjectedFault(
                    f"feeder error injected at item {r} (DKTPU_FAULTS)")
        return self.stage(item)

    def _stage_with_retry(self, r: int, item, tele):
        attempt = 0
        while True:
            try:
                return self._stage_once(r, item)
            except Exception:
                # Only plain Exceptions retry: KeyboardInterrupt/SystemExit
                # and close() must still win immediately.
                if attempt >= self.stage_retries or self._stop.is_set():
                    raise
                tele.counter("resilience.feeder_retries").add(1)
                time.sleep(self.retry_backoff_s * (2 ** attempt))
                attempt += 1

    def _item_source(self) -> Iterator:
        """``(ordinal, item)`` pairs: a range in bounded mode, an enumerate
        of the caller's iterable (offset by ``start_round`` so resume keeps
        fault/ckpt indices stable) in stream mode."""
        if self.num_rounds is not None:
            for r in range(self.start_round, self.num_rounds):
                yield r, r
        else:
            for i, item in enumerate(self.items):
                yield self.start_round + i, item

    def _run(self):
        from distkeras_tpu import telemetry

        tele = telemetry.get()
        stage_span = tele.histogram("feeder.stage")
        try:
            for r, item in self._item_source():
                if self._stop.is_set():
                    return
                t0 = time.perf_counter()
                batch = self._stage_with_retry(r, item, tele)
                # Producer-side cost (gather + transform + device_put), the
                # counterpart of the consumer's ``input_stall``: staging
                # slower than dispatch is what makes stalls appear.
                stage_span.observe(time.perf_counter() - t0)
                if not self._put((r, batch, None)):
                    return
        except BaseException as e:  # noqa: BLE001 - propagate to consumer
            self._put((-1, None, e))
        else:
            self._put((None, None, None))  # sentinel

    def close(self, deadline_s: float = 10.0):
        """Stop the feeder thread and drop all staged batches. Idempotent.

        Bounded: a feeder wedged inside ``stage`` (e.g. a device_put to a
        dead device) cannot be joined — after ``deadline_s`` the daemon
        thread is abandoned so the consumer's original exception still
        propagates instead of hanging the process in a ``finally``."""
        import time

        self._stop.set()
        # Drain so a put blocked on a full queue wakes promptly; staged
        # device-array references die here (including when the feeder thread
        # already finished and left items + sentinel sitting in the queue).
        t_end = time.monotonic() + deadline_s
        while self._thread.is_alive() and time.monotonic() < t_end:
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        with self._q.mutex:
            self._q.queue.clear()

    def __iter__(self) -> Iterator:
        if self._stop.is_set():
            # Closed (or already fully consumed — normal exhaustion closes
            # too): fail loudly rather than silently yielding zero rounds.
            raise RuntimeError(
                "RoundFeeder is closed; construct a new feeder per run")
        from distkeras_tpu import telemetry

        tele = telemetry.get()
        depth_gauge = tele.gauge("feeder.queue_depth")
        fill_gauge = tele.gauge("feeder.fill_ratio")
        stall_counter = tele.counter("resilience.feeder_stall_warnings")
        self._thread.start()
        try:
            wait = 0.0
            next_warn = self.stall_warn
            while True:
                t0 = time.perf_counter()
                try:
                    # Timed get: a concurrent close() suppresses the
                    # sentinel (the stopped feeder never enqueues it), so an
                    # untimed get would block forever.
                    r, batch, err = self._q.get(timeout=0.1)
                except queue.Empty:
                    wait += time.perf_counter() - t0
                    if self._stop.is_set():
                        return
                    # Stall watchdog: exponentially backed-off warnings
                    # (1x, 2x, 4x... the warn threshold) while the data
                    # plane produces nothing, then declare it dead. The
                    # clock is per-round — it resets at every delivery.
                    if wait >= next_warn and next_warn <= self.stall_timeout:
                        stall_counter.add(1)
                        tele.event("feeder_stall", {
                            "waited_s": round(wait, 3),
                            "timeout_s": self.stall_timeout})
                        import warnings as _warnings

                        _warnings.warn(
                            f"input pipeline stalled: no batch for "
                            f"{wait:.1f}s (timeout {self.stall_timeout:.0f}s)",
                            stacklevel=2)
                        next_warn *= 2
                    if wait >= self.stall_timeout:
                        from distkeras_tpu.resilience.errors import (
                            FeederStalledError)

                        tele.counter("resilience.feeder_stall_deaths").add(1)
                        raise FeederStalledError(
                            f"input pipeline produced nothing for "
                            f"{wait:.1f}s (stall_timeout="
                            f"{self.stall_timeout}s); declaring the data "
                            "plane dead")
                    continue
                wait += time.perf_counter() - t0
                next_warn = self.stall_warn
                if err is not None:
                    raise err
                if r is None:
                    return
                # Lookahead health at each pop: depth 0 = the consumer is
                # racing the feeder (stalls imminent); fill 1.0 = staging is
                # fully hidden. qsize() is advisory but cheap and monotone
                # enough for a gauge.
                q = self._q.qsize()
                depth_gauge.set(q)
                fill_gauge.set(q / self.depth)
                self.waits.append(wait)
                self.wait_seconds += wait
                wait = 0.0
                yield r, batch
        finally:
            # Runs on normal exhaustion AND on abandonment (consumer raised /
            # dropped the generator -> GeneratorExit lands at the yield).
            self.close()
