"""Round prefetcher: overlap host-side gather + H2D transfer with device compute.

The reference got pipelining for free from Spark's executor iterators; here a
background thread materializes round ``r+depth`` (native gather) and stages it on
device (``device_put``) while the accelerator crunches round ``r``. jax dispatch is
async, so the main loop's only synchronous cost becomes a queue pop.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator


class RoundFeeder:
    """Iterate ``(r, staged_batch)`` over a BatchPlan with lookahead.

    ``stage(r) -> batch`` does the gather + device_put for round ``r``; it runs on
    the feeder thread. Exceptions propagate to the consumer on the next pop.
    """

    def __init__(self, num_rounds: int, stage: Callable[[int], object],
                 start_round: int = 0, depth: int = 2):
        self.num_rounds = num_rounds
        self.stage = stage
        self.start_round = start_round
        self.depth = max(1, depth)
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        try:
            for r in range(self.start_round, self.num_rounds):
                self._q.put((r, self.stage(r), None))
        except BaseException as e:  # noqa: BLE001 - propagate to consumer
            self._q.put((-1, None, e))
        else:
            self._q.put((None, None, None))  # sentinel

    def __iter__(self) -> Iterator:
        self._thread.start()
        while True:
            r, batch, err = self._q.get()
            if err is not None:
                raise err
            if r is None:
                return
            yield r, batch
