"""Feature transformers — name-for-name parity with ``distkeras/transformers.py``.

The reference's transformers are Spark-ML-style objects with a ``transform(df)``
method (SURVEY.md §2): ``LabelIndexTransformer``, ``OneHotTransformer``,
``MinMaxTransformer``, ``ReshapeTransformer``, ``DenseTransformer``. Same here, over
the numpy-backed :class:`~distkeras_tpu.data.dataframe.DataFrame`. These run once on
the host before training — they are deliberately *not* jitted (one-shot columnar
numpy is faster than staging a compile for a preprocessing pass).
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataframe import DataFrame


class Transformer:
    """Base: ``transform(df) -> df`` (Spark-ML surface the notebooks expect)."""

    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError


class LabelIndexTransformer(Transformer):
    """String/arbitrary labels -> dense integer indices.

    Parity: reference ``LabelIndexTransformer(output_dim, input_col, output_col)``
    which mapped a label column to float indices for Keras.
    """

    def __init__(self, input_col: str = "label", output_col: str = "label_index"):
        self.input_col = input_col
        self.output_col = output_col
        self.classes_: np.ndarray | None = None

    def transform(self, df: DataFrame) -> DataFrame:
        values = df[self.input_col]
        classes, indices = np.unique(values, return_inverse=True)
        self.classes_ = classes
        return df.with_column(self.output_col, indices.astype(np.int32))


class OneHotTransformer(Transformer):
    """Integer labels -> one-hot float vectors.

    Parity: reference ``OneHotTransformer(output_dim, input_col, output_col)``.
    """

    def __init__(self, output_dim: int, input_col: str = "label",
                 output_col: str = "label_one_hot"):
        self.output_dim = output_dim
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, df: DataFrame) -> DataFrame:
        idx = np.asarray(df[self.input_col]).astype(np.int64).reshape(-1)
        if idx.min() < 0 or idx.max() >= self.output_dim:
            raise ValueError(
                f"label index out of range [0, {self.output_dim}): "
                f"[{idx.min()}, {idx.max()}]"
            )
        out = np.zeros((len(idx), self.output_dim), np.float32)
        out[np.arange(len(idx)), idx] = 1.0
        return df.with_column(self.output_col, out)


class MinMaxTransformer(Transformer):
    """Rescale a feature column to ``[o_min, o_max]`` given data range ``[i_min, i_max]``.

    Parity: reference ``MinMaxTransformer(n_min, n_max, o_min, o_max, input_col,
    output_col)`` (used to bring MNIST pixels into [0, 1]).
    """

    def __init__(
        self,
        o_min: float = 0.0,
        o_max: float = 1.0,
        i_min: float | None = None,
        i_max: float | None = None,
        input_col: str = "features",
        output_col: str = "features_normalized",
    ):
        self.o_min, self.o_max = o_min, o_max
        self.i_min, self.i_max = i_min, i_max
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, df: DataFrame) -> DataFrame:
        from distkeras_tpu.data.native_loader import scale_f32

        x = np.ascontiguousarray(df[self.input_col], np.float32)
        i_min = float(x.min()) if self.i_min is None else self.i_min
        i_max = float(x.max()) if self.i_max is None else self.i_max
        scale = (self.o_max - self.o_min) / max(i_max - i_min, 1e-12)
        if scale == 0.0:
            out = np.full_like(x, self.o_min)
        else:
            out = scale_f32(x, i_min, scale, bias=self.o_min)
        return df.with_column(self.output_col, out)


class ReshapeTransformer(Transformer):
    """Reshape each row of a feature column (e.g. 784 -> (28, 28, 1) for convnets).

    Parity: reference ``ReshapeTransformer(input_col, output_col, shape)``.
    """

    def __init__(self, input_col: str, output_col: str, shape: tuple):
        self.input_col = input_col
        self.output_col = output_col
        self.shape = tuple(shape)

    def transform(self, df: DataFrame) -> DataFrame:
        x = np.asarray(df[self.input_col])
        return df.with_column(self.output_col, x.reshape((len(x),) + self.shape))


class DenseTransformer(Transformer):
    """Ensure a feature column is dense float32 (reference: sparse Spark vectors ->
    dense; here: any dtype/object column -> contiguous float32 matrix)."""

    def __init__(self, input_col: str = "features", output_col: str = "features_dense"):
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, df: DataFrame) -> DataFrame:
        x = df[self.input_col]
        if x.dtype == object:
            x = np.stack([np.asarray(row, np.float32) for row in x])
        return df.with_column(self.output_col, np.ascontiguousarray(x, np.float32))
