"""Trainer taxonomy — class-for-class parity with ``distkeras/trainers.py``.

Same names, same constructor-kwargs surface, same ``train(dataframe) -> model`` entry
point (SURVEY.md §2, L5). What changed underneath: ``num_workers`` Spark partitions
become ``num_workers`` chips on a ``data`` mesh; the parameter-server thread becomes a
collective fold (``parallel/disciplines.py``); ``model.train_on_batch`` becomes a
jitted ``lax.scan`` window (``workers.py``).

Trainer -> engine mapping:

* ``SingleTrainer``                  -> SyncEngine on a 1-chip mesh
* ``SynchronousDistributedTrainer``  -> SyncEngine (per-step gradient pmean)
* ``DOWNPOUR/ADAG/DynSGD``           -> AsyncEngine, pull-based folds
* ``AEASGD/EAMSGD``                  -> AsyncEngine, elastic folds
* ``AveragingTrainer``               -> AsyncEngine, no-comm fold + final weight mean
* ``EnsembleTrainer``                -> AsyncEngine, no-comm fold, returns N models
"""

from __future__ import annotations

import time
import warnings
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.data.batching import make_batches
from distkeras_tpu.data.dataframe import DataFrame
from distkeras_tpu.models.base import Model
from distkeras_tpu.parallel.disciplines import (
    ADAGFold,
    AEASGDFold,
    Discipline,
    DownpourFold,
    DynSGDFold,
    EAMSGDFold,
    EnsembleFold,
)
from distkeras_tpu.parallel.engine import AsyncEngine
from distkeras_tpu.parallel.sync import SyncEngine
from distkeras_tpu.runtime import config as runtime_config
from distkeras_tpu.runtime.config import RunConfig
from distkeras_tpu.runtime.mesh import data_mesh

#: Discipline-fold class -> the wire name the netps server folds under
#: (subclass before base: EAMSGDFold is an AEASGDFold).
_FOLD_WIRE_NAMES = (
    (EAMSGDFold, "eamsgd"),
    (AEASGDFold, "aeasgd"),
    (DynSGDFold, "dynsgd"),
    (ADAGFold, "adag"),
    (DownpourFold, "downpour"),
)


def _fold_wire_name(disc: Discipline) -> str:
    for cls, name in _FOLD_WIRE_NAMES:
        if isinstance(disc, cls):
            return name
    raise ValueError(
        f"{type(disc).__name__} has no networked parameter-server "
        "equivalent (only the communicating PS disciplines do)")

#: Socket-era reference kwargs that have no TPU meaning: the parameter-server
#: transport is XLA collectives, so there is no master address/port to bind.
#: Accepted-and-ignored (with a warning) so 2016-era notebooks port by deleting
#: imports, not by editing every constructor call.
_LEGACY_SOCKET_KWARGS = frozenset({"master_port", "master_host", "master", "port"})


def _config_prop(name: str) -> property:
    """Trainer attribute backed by the :class:`RunConfig` (kwargs-first surface
    preserved; assignment rebuilds the frozen config)."""

    def _get(self):
        return getattr(self.config, name)

    def _set(self, value):
        self.config = self.config.replace(**{name: value})

    return property(_get, _set)


class Trainer:
    """Base trainer (reference ``Trainer``): owns model, optimizer, loss, timing.

    ``worker_optimizer`` and ``loss`` accept the reference's Keras-style strings or
    any optax transformation / callable. Hyperparameters normalize into
    ``self.config`` (:class:`RunConfig`); the reference's kwarg names stay
    readable/assignable as properties over it.
    """

    batch_size = _config_prop("batch_size")
    num_epoch = _config_prop("num_epoch")
    learning_rate = _config_prop("learning_rate")
    seed = _config_prop("seed")

    def __init__(
        self,
        model: Model,
        worker_optimizer="sgd",
        loss="categorical_crossentropy",
        features_col: str = "features",
        label_col: str = "label",
        batch_size: int = 32,
        num_epoch: int = 1,
        learning_rate: float = 0.01,
        compute_dtype: Optional[str] = None,
        seed: int = 0,
        metrics_path: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        rounds_per_program: Union[int, str] = 1,
        on_round=None,
        grad_accum: int = 1,
        transform=None,
        device_transform=None,
        normalize_uint8: bool = True,
        **kwargs,
    ):
        legacy = {k: kwargs.pop(k) for k in list(kwargs) if k in _LEGACY_SOCKET_KWARGS}
        if "parallel" in kwargs:
            # Targeted, not a bare TypeError: a user who learned parallel=
            # on ADAG will try it on the ensemble/averaging/sync trainers.
            raise ValueError(
                f"{type(self).__name__} does not host model-parallel "
                "submeshes. parallel={'model': tp, 'seq': sp} is supported "
                "by the communicating async trainers (DOWNPOUR/ADAG/DynSGD/"
                "AEASGD/EAMSGD — each worker becomes a tp[ x sp] submesh); "
                "for model-parallel synchronous training use "
                "ParallelTrainer(parallel={'data': ..., 'model': ...}). "
                "Averaging/Ensemble fold non-communicating replicas and "
                "have no submesh variant.")
        if kwargs:
            raise TypeError(
                f"{type(self).__name__} got unexpected kwargs: {sorted(kwargs)}"
            )
        if legacy:
            warnings.warn(
                f"ignoring socket-era kwargs {sorted(legacy)}: the parameter "
                "server is an XLA collective fold on TPU — there is no master "
                "address/port (kept for reference-notebook compatibility)",
                DeprecationWarning,
                stacklevel=2,
            )
        if not normalize_uint8 and getattr(model, "normalize_uint8", True):
            # The flag lives on the Model (engines, the remote worker loop,
            # and predictors all read it there — train and inference can
            # never disagree); the Trainer kwarg is the opt-out surface.
            import dataclasses as _dc

            model = _dc.replace(model, normalize_uint8=False)
        self.model = model
        self.worker_optimizer = worker_optimizer
        self.loss = loss
        self.features_col = features_col
        self.label_col = label_col
        if isinstance(compute_dtype, (str, type(None))):
            dtype_str, self._dtype_override = compute_dtype, None
        else:  # a concrete jnp dtype: bypasses the string-keyed config
            dtype_str, self._dtype_override = None, compute_dtype
        self.config = RunConfig(
            batch_size=batch_size, num_epoch=num_epoch,
            learning_rate=learning_rate, compute_dtype=dtype_str, seed=seed,
        )
        self.metrics_path = metrics_path
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.resume = resume
        #: fold rounds per dispatched XLA program (1 = a program per round).
        #: Semantics-preserving dispatch amortization: raise it when host
        #: dispatch latency, not the device, bounds small-model throughput.
        #: Checkpoints then land on block boundaries (exact-resume-safe).
        #: ``"auto"`` probes the per-round wall time and sizes R to fill
        #: ~64 ms of device work per program (engine._AUTO_TARGET_S) — the
        #: right default for small models on dispatch-latency-heavy paths
        #: (no hand tuning).
        if rounds_per_program == "auto":
            self.rounds_per_program: Union[int, str] = "auto"
        elif (isinstance(rounds_per_program, str)
              or int(rounds_per_program) < 1):
            raise ValueError(
                f"rounds_per_program must be an int >= 1 or 'auto', got "
                f"{rounds_per_program!r}")
        else:
            self.rounds_per_program = int(rounds_per_program)
        #: optional ``f(round, loss)`` fired after every fold round (the
        #: Keras-callback-shaped progress hook; reference workers printed
        #: per-batch logs on executors — here the driver sees every round).
        self.on_round = on_round
        #: micro-batches per optimizer step (1/A the activation memory — for
        #: batches that don't fit HBM; see workers.make_local_loop for the
        #: BatchNorm/dropout semantics caveat).
        self.grad_accum = int(grad_accum)
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        #: optional training-time row transform ``fn(features, labels, rng)
        #: -> (features, labels)`` applied to every staged round
        #: (deterministic per (seed, round, worker) — the lazy Spark-pipeline
        #: half: per-epoch randomized augmentation, train-time normalization;
        #: works for in-RAM and sharded dataframes alike). See
        #: ``data.batching.apply_round_transform``.
        self.transform = transform
        #: optional ON-DEVICE per-step transform ``fn(rng, x, y) -> (x, y)``
        #: applied inside the jitted round program (``ops/augment.py``) —
        #: image augmentation at VPU cost with raw uint8 staged over PCIe,
        #: vs ``transform``'s host-numpy cost. Deterministic per
        #: (seed, round, worker) like the host hook.
        self.device_transform = device_transform
        self.history: np.ndarray | None = None
        self.worker_histories: np.ndarray | None = None
        self.training_time: float = 0.0
        self._t_start: float | None = None

    @property
    def compute_dtype(self):
        if self._dtype_override is not None:
            return self._dtype_override
        return self.config.dtype

    @compute_dtype.setter
    def compute_dtype(self, value):
        if isinstance(value, (str, type(None))):
            self._dtype_override = None
            self.config = self.config.replace(compute_dtype=value)
        else:
            self._dtype_override = value

    def _restore_candidate(self, engine, plan, ckpt, step, meta):
        """Restore checkpoint ``step`` (whose sidecar ``meta`` was already
        read) onto ``engine``, integrity-verified against the digest sidecar.
        Returns ``(state, start_round)``; raises on a missing/corrupt
        payload so :meth:`_resume_from_checkpoint` can fall back."""
        if not meta:
            # Orbax steps are offset from rounds across resumes; with
            # the sidecar gone the raw step is only an upper bound on
            # the true round. Resume conservatively from it, loudly.
            warnings.warn(
                f"checkpoint step {step} has no meta sidecar; "
                "treating the step as the round index — if this run "
                "chain was ever resumed or resized, data progress "
                "may be overestimated", stacklevel=2)
        true_round = int(meta.get("round", step))
        saved_w = meta.get("num_workers")
        cur_w = getattr(engine, "num_workers", None)
        saved_spr = meta.get("samples_per_round")
        resized = (saved_w is not None and cur_w is not None
                   and saved_w != cur_w)
        # Round indices are meaningless across schedules whose
        # per-round sample count changed — a worker-count resize,
        # OR a topology-dependent plan (e.g. a step engine's
        # per-dp-rank sharded schedule) whose spr moved while the
        # engine's logical worker count stayed 1.
        spr_changed = (saved_spr is not None
                       and saved_spr != plan.samples_per_round)
        start = 0
        if resized or spr_changed:
            # Carry over DATA progress (samples consumed), not the
            # raw counter. Old checkpoints without samples_per_round
            # meta fall back to the worker-count ratio (exact when
            # batch/window are unchanged, the common pod-resize
            # case).
            num = saved_spr if saved_spr else saved_w
            den = plan.samples_per_round if saved_spr else cur_w
            start = min(((true_round + 1) * num) // den,
                        plan.num_rounds)
        if resized and hasattr(engine, "host_state"):
            # Elastic resume: the checkpoint was written at a
            # different worker count (pod resize). Restore on the
            # host at the saved topology, then re-join every worker
            # from the center (the reference's PS pull semantics).
            host = ckpt.restore_host(engine.host_state(saved_w),
                                     step=step, verify=True)
            state = engine.adopt_state(host)
        else:
            state = ckpt.restore(engine.init_state(), step=step, verify=True)
            if resized:
                # W-independent state (e.g. SyncEngine) restores
                # exactly under a resize; data progress still
                # rescales so the resumed run neither replays nor
                # skips a topology-dependent slice of the data.
                warnings.warn(
                    f"resuming a checkpoint saved with num_workers="
                    f"{saved_w} on num_workers={cur_w}: state "
                    "restored exactly; data progress rescaled",
                    stacklevel=2)
            elif spr_changed:
                warnings.warn(
                    "resuming under a schedule whose samples/round "
                    f"changed ({saved_spr} -> "
                    f"{plan.samples_per_round}): state restored "
                    "exactly; data progress rescaled", stacklevel=2)
            else:
                start = min(true_round + 1, plan.num_rounds)
        return state, start

    def _resume_from_checkpoint(self, engine, plan, ckpt):
        """Resolve the resume point over ALL retained steps, newest first:
        steps with an intact meta sidecar are preferred (a missing/corrupt
        sidecar falls back to the most recent step that has one), and a step
        whose payload fails to restore or fails its integrity check falls
        back to the previous step. Returns ``(state, start, step_offset)``;
        ``state`` is None when nothing was restorable (fresh start)."""
        from distkeras_tpu import telemetry
        from distkeras_tpu.checkpoint import resume_candidates

        steps = ckpt.steps_desc()
        candidates = resume_candidates(
            steps, lambda s: ckpt.meta(s) is not None)
        if steps and candidates[0] != steps[0]:
            telemetry.counter("resilience.ckpt_fallback_steps").add(1)
            warnings.warn(
                f"latest checkpoint step {steps[0]} has a missing/corrupt "
                f"meta sidecar; falling back to step {candidates[0]}, the "
                "most recent step with an intact sidecar", stacklevel=2)
        last_err = None
        for step in candidates:
            meta = ckpt.meta(step) or {}
            saved_w = meta.get("num_workers")
            cur_w = getattr(engine, "num_workers", None)
            if (saved_w is not None and cur_w is not None
                    and saved_w != cur_w and hasattr(engine, "host_state")):
                disc = getattr(engine, "discipline", None)
                if disc is not None and not disc.center_is_trained:
                    # A configuration error, not corruption: falling back
                    # to an older step cannot fix a topology mismatch.
                    raise ValueError(
                        f"cannot elastically resume {type(disc).__name__}"
                        " (worker count changed): its training progress"
                        " lives in the per-worker replicas, not the"
                        " center. Resume with the original num_workers="
                        f"{saved_w}.")
            try:
                state, start = self._restore_candidate(
                    engine, plan, ckpt, step, meta)
            except Exception as e:  # corrupt/unreadable: try the next step
                last_err = e
                telemetry.counter("resilience.ckpt_fallback_steps").add(1)
                telemetry.event("ckpt_fallback", {
                    "step": step, "error": repr(e)})
                warnings.warn(
                    f"checkpoint step {step} failed to restore "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous step", stacklevel=2)
                continue
            # Offset past the NEWEST retained step, not the restored one:
            # after a fallback the skipped (corrupt/sidecar-less) newer
            # steps are still on disk, and Orbax declines any save at a
            # step <= latest_step() — offsetting from the restored step
            # would get every periodic save until the counter passed them
            # silently declined.
            return state, start, (steps[0] + 1) - start
        warnings.warn(
            f"no restorable checkpoint in {self.checkpoint_dir} "
            f"(last error: {last_err!r}); starting fresh", stacklevel=2)
        return None, 0, (steps[0] + 1) if steps else 0

    def _execute(self, engine, plan):
        """Shared run harness: resume from checkpoint, per-round metrics/saves."""
        state = None
        start = 0
        # Orbax step = round + step_offset. Orbax declines saves at any
        # step <= latest_step, and elastic resume can map the resume round
        # BELOW the saved step (scale-up: start = (r+1)*saved_w//cur_w < r) —
        # without an offset every post-resize checkpoint would be silently
        # dropped until the counter passed the old step. The offset keeps the
        # Orbax step sequence strictly increasing across any chain of resumes
        # while ``meta["round"]`` records the true (topology-local) round.
        step_offset = 0
        ckpt = logger = None
        if self.checkpoint_dir:
            from distkeras_tpu.checkpoint import Checkpointer

            ckpt = Checkpointer(self.checkpoint_dir)
            latest = ckpt.latest_step()
            if self.resume and latest is not None:
                state, start, step_offset = self._resume_from_checkpoint(
                    engine, plan, ckpt)
            elif latest is not None:
                # Fresh run (resume=False) into a dir with prior checkpoints:
                # rounds restart at 0, so without an offset every save would
                # land at a step Orbax has already seen and be declined.
                step_offset = latest + 1
        if state is None:
            state = engine.init_state()
        if self.metrics_path:
            from distkeras_tpu.metrics import MetricsLogger
            from distkeras_tpu.telemetry.training import DisciplineMonitor

            logger = MetricsLogger(
                self.metrics_path,
                samples_per_round=plan.samples_per_round,
                # Step engines run one logical plan-worker over many chips;
                # they expose the true chip count for samples/s/chip.
                num_chips=getattr(engine, "num_chips", plan.num_workers),
                extra={"trainer": type(self).__name__},
                # Discipline-aware round fields (staleness rotation, DynSGD
                # scales, per-worker loss divergence, straggler flags) for
                # engines that have a discipline; inert otherwise.
                monitor=DisciplineMonitor(
                    discipline=getattr(engine, "discipline", None),
                    num_workers=getattr(engine, "num_workers", 1)),
            )

        save_due = [False]  # a scheduled save passed while no state was out

        def _meta(r):
            return {"num_workers": getattr(engine, "num_workers", 1),
                    "round": r,
                    "samples_per_round": plan.samples_per_round}

        def on_round(r, loss, st):
            if logger is not None:
                # st=None marks interior rounds of a compiled block (the
                # engine contract) — the logger's authoritative burst-tail
                # signal for segmentation and straggler flagging.
                logger(r, loss, st)
            if self.on_round is not None:
                self.on_round(r, loss)
            if ckpt is None or not self.checkpoint_every:
                return
            if (r + 1) % self.checkpoint_every == 0 or r == plan.num_rounds - 1:
                save_due[0] = True
            # With rounds_per_program > 1 only block-final rounds carry a
            # state (interior states never exist on the host); a due save
            # waits for the next state-bearing call, whose label ``r`` is the
            # true round of that state — resume stays exact.
            if save_due[0] and st is not None:
                # wait=True: the engine donates state buffers into the next
                # round; the write must complete before training continues.
                # A declined save (e.g. another writer advanced the manager's
                # latest_step) keeps the save due, to retry at the next
                # state-bearing round instead of silently dropping it.
                if ckpt.save(r + step_offset, st, wait=True, meta=_meta(r)):
                    save_due[0] = False

        import contextlib

        done = False
        try:
            state, losses = engine.run(
                plan, state=state, start_round=start, on_round=on_round,
                rounds_per_program=self.rounds_per_program)
            if ckpt is not None and save_due[0] and plan.num_rounds > start:
                # The final scheduled save was declined (e.g. another writer
                # advanced the manager's latest_step past our sequence) and
                # there was no later round to retry at — persist the
                # terminal state at the next step the manager will accept.
                final_r = plan.num_rounds - 1
                latest_now = ckpt.latest_step()
                step = max(final_r + step_offset,
                           (-1 if latest_now is None else latest_now) + 1)
                ckpt.save(step, state, wait=True, meta=_meta(final_r))
            # Happy path closes UNsuppressed: a failed final checkpoint
            # flush must surface, not vanish into a finally.
            if ckpt is not None:
                ckpt.close()
            if logger is not None:
                logger.close()
            done = True
        finally:
            # Failure path (including a close that itself raised): orbax's
            # background threads and the metrics file handle must not leak
            # across in-process retries. Close errors are suppressed (an
            # in-flight async save can raise from wait_until_finished) so
            # the root-cause exception propagates; MetricsLogger.close is
            # idempotent, so the clean-exit double call is a no-op.
            if not done:
                if ckpt is not None:
                    with contextlib.suppress(Exception):
                        ckpt.close()
                if logger is not None:
                    with contextlib.suppress(Exception):
                        logger.close()
        losses = np.asarray(losses)
        if losses.ndim == 2:  # async engines: [rounds, W] per-worker curves
            self.worker_histories = losses.T
            self.history = losses.mean(axis=1)
        else:
            self.worker_histories = None
            self.history = losses
        return state

    def _finish_model(self, params, engine_state, worker: Optional[int] = None,
                      state_reduce=None) -> Model:
        """Model with trained params + (if the model is stateful) the trained
        mutable collections (BatchNorm running stats).

        Async engines stack state ``[W, ...]``: pass ``worker`` to take one
        member's copy (synced disciplines keep all copies equal, so 0 is
        canonical) or ``state_reduce`` to aggregate (AveragingTrainer)."""
        m = self.model.with_params(params)
        trained_state = getattr(engine_state, "model_state", None)
        if trained_state is not None:
            if state_reduce is not None:
                trained_state = jax.tree.map(state_reduce, trained_state)
            elif worker is not None:
                trained_state = jax.tree.map(lambda a: a[worker], trained_state)
            m = m.with_state(jax.tree.map(np.asarray, trained_state))
        return m

    # -- timing parity (reference Trainer.record_training_start/stop) -------
    def record_training_start(self):
        self._t_start = time.perf_counter()

    def record_training_stop(self):
        self.training_time = time.perf_counter() - self._t_start

    def get_training_time(self) -> float:
        return self.training_time

    def get_history(self) -> np.ndarray:
        return self.history

    def get_worker_histories(self) -> Optional[np.ndarray]:
        """Per-worker loss curves, shape ``[num_workers, rounds]`` (reference
        parity: per-worker Keras history collected on the driver; SURVEY.md §5
        metrics row). ``None`` for sync engines, whose replicas never diverge."""
        return self.worker_histories

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> Model:
        raise NotImplementedError


class SingleTrainer(Trainer):
    """One-replica baseline (reference ``SingleTrainer``): coalesce to a single
    worker, plain minibatch SGD, no communication."""

    def __init__(self, *args, steps_per_program: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        self.steps_per_program = steps_per_program

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> Model:
        self.record_training_start()
        mesh = data_mesh(num_workers=1)
        engine = SyncEngine(
            self.model, self.worker_optimizer, self.loss, mesh,
            learning_rate=self.learning_rate, compute_dtype=self.compute_dtype,
            seed=self.seed, grad_accum=self.grad_accum,
            device_transform=self.device_transform,
        )
        plan = make_batches(
            dataframe, self.features_col, self.label_col, self.batch_size,
            num_workers=1, window=self.steps_per_program, num_epoch=self.num_epoch,
            shuffle=shuffle, seed=self.seed, transform=self.transform,
        )
        state = self._execute(engine, plan)
        self.record_training_stop()
        return self._finish_model(state.params, state)


class DistributedTrainer(Trainer):
    """Base for multi-worker trainers (reference ``DistributedTrainer``)."""

    num_workers = _config_prop("num_workers")

    def __init__(self, *args, num_workers: Optional[int] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.config = self.config.replace(num_workers=num_workers)

    def _mesh(self):
        """(mesh, workers_per_chip): ``num_workers`` is a *logical* worker
        count (the reference's Spark-executor count — 8 workers on a laptop
        was normal), so counts beyond the chip count multiplex m workers
        onto each chip instead of erroring."""
        w = self.num_workers
        devices = jax.device_count()
        if w is None or w <= devices:
            return data_mesh(num_workers=w), 1
        if w % devices == 0:
            return data_mesh(), w // devices
        raise ValueError(
            f"num_workers={w} exceeds the {devices} available chips and "
            f"does not divide evenly onto them; use a multiple of {devices} "
            "(m workers per chip) or at most the chip count")


class SynchronousDistributedTrainer(DistributedTrainer):
    """Per-step gradient all-reduce (reference ``SynchronousDistributedTrainer``;
    BASELINE config #5's "synchronous DOWNPOUR" at scale)."""

    def __init__(self, *args, steps_per_program: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        self.steps_per_program = steps_per_program

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> Model:
        self.record_training_start()
        mesh, m = self._mesh()
        engine = SyncEngine(
            self.model, self.worker_optimizer, self.loss, mesh,
            learning_rate=self.learning_rate, compute_dtype=self.compute_dtype,
            seed=self.seed, grad_accum=self.grad_accum, workers_per_chip=m,
            device_transform=self.device_transform,
        )
        plan = make_batches(
            dataframe, self.features_col, self.label_col, self.batch_size,
            num_workers=engine.num_workers, window=self.steps_per_program,
            num_epoch=self.num_epoch, shuffle=shuffle, seed=self.seed, transform=self.transform,
        )
        state = self._execute(engine, plan)
        self.record_training_stop()
        return self._finish_model(state.params, state)


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Base for the discipline trainers (reference
    ``AsynchronousDistributedTrainer``): K local steps per worker per fold round."""

    communication_window = _config_prop("communication_window")

    def __init__(self, *args, communication_window: int = 5,
                 parallel: Optional[dict] = None, rules=None,
                 divergence_reset: Optional[float] = None,
                 remote: Optional[str] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.config = self.config.replace(communication_window=communication_window)
        #: ``"host:port"`` of a networked parameter server (netps): the
        #: worker loop becomes pull -> K local steps -> commit through the
        #: hardened TCP client instead of the in-process collective fold.
        #: Defaults from DKTPU_PS_ENDPOINT (set by Job for launched pods).
        self.remote = remote
        if remote and parallel:
            raise ValueError(
                "remote= (networked parameter server) and parallel= "
                "(model-parallel submeshes) cannot combine: the remote "
                "worker loop runs whole-model replicas")
        #: resilience: |worker loss − mean| beyond this threshold re-adopts
        #: the center for that worker (fresh optimizer, reference PS-pull
        #: semantics). None (default) = off; fetches the loss every round
        #: when on. Env override: DKTPU_DIVERGENCE_RESET.
        self.divergence_reset = divergence_reset
        #: each async worker as a model-parallel submesh:
        #: ``parallel={"model": 2}`` makes every logical worker a tp=2
        #: tensor-parallel replica (AsyncTPEngine over a (data, model)
        #: mesh); ``rules`` overrides the PartitionSpec rule set (default
        #: TRANSFORMER_TP_RULES).
        self.parallel = dict(parallel) if parallel else None
        self.rules = rules

    def _discipline(self) -> Discipline:
        raise NotImplementedError

    def _tp_engine(self):
        from distkeras_tpu.parallel.async_tp import AsyncTPEngine
        from distkeras_tpu.parallel.sharding import TRANSFORMER_TP_RULES
        from distkeras_tpu.runtime.mesh import hybrid_mesh

        axes = dict(self.parallel)
        tp = int(axes.pop("model", 1))
        sp = int(axes.pop("seq", 1))
        if axes:
            raise ValueError(
                f"async parallel supports only {{'model': n}} and "
                f"{{'seq': s}}, got extra axes {sorted(axes)}; pipeline/"
                "expert parallel compose via ParallelTrainer instead")
        devices = jax.device_count()
        W = self.num_workers or devices // (tp * sp)
        if W < 1 or W * tp * sp > devices:
            raise ValueError(
                f"parallel={{'model': {tp}, 'seq': {sp}}} with "
                f"num_workers={self.num_workers} needs num_workers*{tp * sp} "
                f"<= {devices} available devices (and at least one worker); "
                f"got W={W}")
        model = self.model
        layout = {"data": W, "model": tp}
        if sp > 1 or getattr(model.module, "seq_axis", None) is not None:
            # seq between data and model: ring ppermutes ride faster links
            # than the worker fold, TP all-reduces the fastest.
            layout = {"data": W, "seq": sp, "model": tp}
        if sp > 1 and getattr(model.module, "seq_axis", None) is None:
            # Same rebind ParallelTrainer does: a module built without
            # seq_axis would silently use local positions under sequence
            # sharding. Dense/flash attention falls back to gather-SP;
            # 'ring' must be requested at model construction.
            if not hasattr(model.module, "seq_axis"):
                raise ValueError(
                    f"parallel={self.parallel} has a 'seq' axis but "
                    f"{type(model.module).__name__} is not sequence-"
                    "shardable (no seq_axis attribute)")
            from distkeras_tpu.runtime.mesh import SEQ_AXIS

            model = model.with_module(model.module.clone(seq_axis=SEQ_AXIS))
        mesh = hybrid_mesh(layout)
        rules = self.rules if self.rules is not None else TRANSFORMER_TP_RULES
        return AsyncTPEngine(
            model, self.worker_optimizer, self.loss, self._discipline(),
            mesh, window=self.communication_window, rules=rules,
            learning_rate=self.learning_rate,
            compute_dtype=self.compute_dtype, seed=self.seed,
            grad_accum=self.grad_accum,
            device_transform=self.device_transform,
            divergence_reset=self.divergence_reset,
        )

    def _run(self, dataframe: DataFrame, shuffle: bool):
        if self.parallel:
            engine = self._tp_engine()
        else:
            mesh, m = self._mesh()
            engine = AsyncEngine(
                self.model, self.worker_optimizer, self.loss,
                self._discipline(), mesh,
                window=self.communication_window,
                learning_rate=self.learning_rate,
                compute_dtype=self.compute_dtype, seed=self.seed,
                grad_accum=self.grad_accum, workers_per_chip=m,
                device_transform=self.device_transform,
                divergence_reset=self.divergence_reset,
            )
        plan = make_batches(
            dataframe, self.features_col, self.label_col, self.batch_size,
            num_workers=engine.num_workers, window=self.communication_window,
            num_epoch=self.num_epoch, shuffle=shuffle, seed=self.seed, transform=self.transform,
        )
        return self._execute(engine, plan)

    def _remote_endpoint(self) -> Optional[str]:
        return self.remote or runtime_config.env_str("DKTPU_PS_ENDPOINT") or None

    def _train_remote(self, dataframe: DataFrame, shuffle: bool,
                      endpoint: str) -> Model:
        """The networked-PS path: N worker threads, each pull -> K jitted
        local steps -> commit over TCP through the hardened client
        (``netps/remote.py``); returns the server's final center."""
        from distkeras_tpu.netps.remote import run_remote
        from distkeras_tpu.ops.losses import get_loss
        from distkeras_tpu.ops.optimizers import get_optimizer

        if self.checkpoint_dir or self.metrics_path:
            warnings.warn(
                "remote= training does not drive the checkpoint/metrics "
                "harness: the parameter-server process owns the center; "
                "checkpoint_dir/metrics_path are ignored on this path",
                stacklevel=2)
        W = self.num_workers or jax.device_count()
        plan = make_batches(
            dataframe, self.features_col, self.label_col, self.batch_size,
            num_workers=W, window=self.communication_window,
            num_epoch=self.num_epoch, shuffle=shuffle, seed=self.seed,
            transform=self.transform,
        )
        disc = self._discipline()
        params, losses = run_remote(
            endpoint=endpoint, model=self.model,
            tx=get_optimizer(self.worker_optimizer, self.learning_rate),
            loss_fn=get_loss(self.loss), plan=plan,
            discipline=_fold_wire_name(disc),
            window=self.communication_window,
            alpha=getattr(disc, "alpha", 0.05), seed=self.seed,
            compute_dtype=self.compute_dtype, grad_accum=self.grad_accum,
        )
        self.worker_histories = losses.T
        self.history = np.nanmean(losses, axis=1)
        return self.model.with_params(params)

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> Model:
        self.record_training_start()
        endpoint = self._remote_endpoint()
        if endpoint:
            # Re-check here, not only in __init__: the endpoint may arrive
            # via DKTPU_PS_ENDPOINT (a Job-launched pod sets it for every
            # worker), and silently dropping a requested model-parallel
            # layout would be far worse than refusing.
            if self.parallel:
                raise ValueError(
                    f"parameter-server endpoint {endpoint!r} (remote= or "
                    "DKTPU_PS_ENDPOINT) cannot combine with parallel=: the "
                    "remote worker loop runs whole-model replicas")
            model = self._train_remote(dataframe, shuffle, endpoint)
            self.record_training_stop()
            return model
        state = self._run(dataframe, shuffle)
        self.record_training_stop()
        return self._finish_model(state.center, state, worker=0)


class DOWNPOUR(AsynchronousDistributedTrainer):
    """DOWNPOUR (reference ``DOWNPOUR`` trainer + ``DeltaParameterServer``)."""

    def _discipline(self):
        return DownpourFold()


class ADAG(AsynchronousDistributedTrainer):
    """ADAG (reference ``ADAG`` trainer + ``ADAGParameterServer``): window-normalized
    accumulated-gradient commits."""

    def _discipline(self):
        return ADAGFold()


class DynSGD(AsynchronousDistributedTrainer):
    """DynSGD (reference ``DynSGD`` trainer + ``DynSGDParameterServer``):
    staleness-scaled folds."""

    def _discipline(self):
        return DynSGDFold()


class AEASGD(AsynchronousDistributedTrainer):
    """Elastic averaging (reference ``AEASGD``): exploration via persistent local
    replicas tethered to the center with elastic rate ``α = ρ·learning_rate``."""

    def __init__(self, *args, rho: float = 5.0, **kwargs):
        super().__init__(*args, **kwargs)
        self.rho = rho

    def _discipline(self):
        return AEASGDFold(alpha=self.rho * self.learning_rate)


class EAMSGD(AsynchronousDistributedTrainer):
    """EAMSGD (reference ``EAMSGD``): AEASGD with momentum local workers."""

    def __init__(self, *args, rho: float = 5.0, momentum: float = 0.9, **kwargs):
        super().__init__(*args, **kwargs)
        self.rho = rho
        self.momentum = momentum
        # Momentum lives in the *local* optimizer (reference EAMSGDWorker).
        if self.worker_optimizer in ("sgd", "momentum", "nesterov"):
            import optax

            self.worker_optimizer = optax.sgd(
                self.learning_rate, momentum=self.momentum,
                nesterov=self.worker_optimizer == "nesterov",
            )
        else:
            import warnings

            warnings.warn(
                "EAMSGD: momentum kwarg is embedded in the local optimizer; the "
                f"provided worker_optimizer={self.worker_optimizer!r} is used as-is "
                "and the momentum argument is ignored",
                stacklevel=2,
            )

    def _discipline(self):
        return EAMSGDFold(alpha=self.rho * self.learning_rate)


class ParallelTrainer(Trainer):
    """One-class trainer for the beyond-reference model-parallel engines —
    tensor/sequence/expert/pipeline parallelism with the reference's
    ``train(dataframe)`` UX and the full run harness (checkpoint/resume,
    metrics JSONL, ``rounds_per_program``) the data-parallel trainers get
    from :meth:`Trainer._execute`.

    ``parallel`` is the mesh layout, ``{axis: size}`` with at most one ``-1``
    (inferred): e.g. ``{'data': -1, 'model': 2}`` (dp×tp),
    ``{'data': 2, 'pipe': 4}`` (dp×pp), ``{'data': 2, 'expert': 4}``
    (dp×ep MoE), ``{'data': -1, 'seq': 2, 'model': 2}`` (dp×sp×tp).
    Put the most-communicating axis last — it lands on adjacent ICI links.

    ``strategy`` picks the engine; ``"auto"`` resolves from the mesh and
    model: a ``pipe`` axis → :class:`PipelineEngine` (GPipe microbatching),
    a ``seq`` axis / ring-sharded or flash-attention module →
    :class:`SPMDEngine` (shard_map dp×sp + GSPMD tp), anything else →
    :class:`GSPMDEngine` (pure sharding annotations; MoE all-to-alls and TP
    all-reduces are XLA-inserted).

    ``batch_size`` is the **global** per-step batch (the mesh is one logical
    worker), unlike the data-parallel trainers' per-worker batch; it must
    divide by the ``data`` axis (and ``num_microbatches`` for pipeline).
    """

    def __init__(
        self,
        model: Model,
        parallel: Optional[dict] = None,
        strategy: str = "auto",
        tp_rules=None,
        steps_per_program: int = 4,
        num_microbatches: int = 4,
        aux_loss_weight: float = 0.0,
        **kwargs,
    ):
        super().__init__(model, **kwargs)
        if self.grad_accum != 1:
            raise ValueError(
                "ParallelTrainer does not support grad_accum: the step "
                "engines have no accumulation path, so the kwarg would be "
                "silently ignored. Raise batch_size (the engines shard it "
                "over the data axis) or use a data-parallel trainer.")
        self.parallel = dict(parallel) if parallel else {"data": -1}
        if "data" not in self.parallel:
            self.parallel = {"data": 1, **self.parallel}
        if strategy not in ("auto", "spmd", "gspmd", "pipeline"):
            raise ValueError(
                f"strategy must be auto|spmd|gspmd|pipeline, got {strategy!r}")
        self.strategy = strategy
        self.tp_rules = tp_rules
        self.steps_per_program = int(steps_per_program)
        self.num_microbatches = int(num_microbatches)
        self.aux_loss_weight = float(aux_loss_weight)

    def _resolve_strategy(self) -> str:
        if self.strategy != "auto":
            return self.strategy
        if self.parallel.get("pipe", 1) != 1:
            return "pipeline"
        mod = self.model.module
        if (self.parallel.get("seq", 1) != 1
                or getattr(mod, "seq_axis", None) is not None
                or getattr(mod, "attn_impl", None) == "flash"):
            # flash/ring need a shard_map-bound mesh axis (GSPMDEngine
            # rejects them at construction by design).
            return "spmd"
        return "gspmd"

    def _default_rules(self):
        from distkeras_tpu.parallel.sharding import (
            MOE_RULES, TRANSFORMER_TP_RULES)

        if self.parallel.get("expert", 1) != 1:
            return MOE_RULES
        return TRANSFORMER_TP_RULES

    def _build_engine(self):
        from distkeras_tpu.parallel.runner import WindowedStepEngine
        from distkeras_tpu.runtime.mesh import SEQ_AXIS, hybrid_mesh

        strat = self._resolve_strategy()
        layout = dict(self.parallel)
        if strat == "spmd":
            # SPMDEngine always shard_maps over (data, seq); a dp×tp request
            # routed here (flash/ring models) still needs the axis present.
            layout.setdefault("seq", 1)
        mesh = hybrid_mesh(layout)
        model = self.model
        if (mesh.shape.get("seq", 1) > 1  # resolved size: -1 is inferred here
                and getattr(model.module, "seq_axis", None) is None):
            # Sequence sharding changes how the module computes positions and
            # attention; a module built without seq_axis would silently use
            # local positions. Rebind the same params under a seq-aware
            # module (dense/flash attention falls back to gather-SP; 'ring'
            # must be requested explicitly at model construction).
            if not hasattr(model.module, "seq_axis"):
                raise ValueError(
                    f"parallel={self.parallel} has a 'seq' axis but "
                    f"{type(model.module).__name__} is not sequence-"
                    "shardable (no seq_axis attribute)")
            model = model.with_module(
                model.module.clone(seq_axis=SEQ_AXIS))
        rules = self.tp_rules if self.tp_rules is not None else self._default_rules()
        common = dict(learning_rate=self.learning_rate, seed=self.seed,
                      compute_dtype=self.compute_dtype)
        if strat == "pipeline":
            from distkeras_tpu.parallel.pipeline_engine import PipelineEngine

            inner = PipelineEngine(
                model, self.worker_optimizer, self.loss, mesh,
                num_microbatches=self.num_microbatches, **common)
        elif strat == "spmd":
            from distkeras_tpu.parallel.spmd import SPMDEngine

            inner = SPMDEngine(
                model, self.worker_optimizer, self.loss, mesh, rules,
                aux_loss_weight=self.aux_loss_weight, **common)
        else:
            from distkeras_tpu.parallel.gspmd import GSPMDEngine

            inner = GSPMDEngine(
                model, self.worker_optimizer, self.loss, mesh, rules,
                aux_loss_weight=self.aux_loss_weight, **common)
        return WindowedStepEngine(inner, self.steps_per_program)

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> Model:
        self.record_training_start()
        engine = self._build_engine()
        # Multi-process sharded stores plan one "worker" per dp rank so each
        # host stages only its own ranks' rows (the engine merges the
        # rank-major stack back into the global batch — a sharding-preserving
        # reshape). Everything else uses the whole-mesh single-worker plan.
        plan_workers, per_worker_batch = 1, self.batch_size
        if (getattr(dataframe, "is_sharded", False)
                and jax.process_count() > 1):
            plan_workers = engine.dp_size
            if self.batch_size % plan_workers:
                raise ValueError(
                    f"batch_size={self.batch_size} must divide by the data-"
                    f"parallel size {plan_workers} for multi-process sharded "
                    "stores (rows are staged per dp rank)")
            per_worker_batch = self.batch_size // plan_workers
        plan = make_batches(
            dataframe, self.features_col, self.label_col, per_worker_batch,
            num_workers=plan_workers, window=self.steps_per_program,
            num_epoch=self.num_epoch, shuffle=shuffle, seed=self.seed, transform=self.transform,
        )
        state = self._execute(engine, plan)
        self.record_training_stop()
        inner = engine.inner
        if hasattr(inner, "export_params"):  # pipeline: merge stage stacks
            params = inner.export_params(state)
        else:
            params = jax.device_get(state.params)
        return self.model.with_params(params)


#: The flagship-model spelling (VERDICT r2 next-round #3 names it this way).
TransformerTrainer = ParallelTrainer


class AveragingTrainer(DistributedTrainer):
    """Train independent replicas, average their weights (reference
    ``AveragingTrainer``): the fold is a single ``pmean`` at the end, here computed
    from the stacked local replicas."""

    communication_window = _config_prop("communication_window")

    def __init__(self, *args, communication_window: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        # steps per program only (no semantic effect: the fold is a no-op)
        self.config = self.config.replace(communication_window=communication_window)

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> Model:
        self.record_training_start()
        mesh, m = self._mesh()
        # NOTE: replicas deliberately share one init (per_worker_init=False).
        # Post-hoc *weight* averaging is only meaningful when all replicas
        # descend within one loss basin; averaging independently-initialized
        # nets produces a point between basins (verified: accuracy collapses).
        # The reference likewise broadcast one serialized model to executors.
        engine = AsyncEngine(
            self.model, self.worker_optimizer, self.loss, EnsembleFold(), mesh,
            window=self.communication_window, learning_rate=self.learning_rate,
            compute_dtype=self.compute_dtype, seed=self.seed,
            grad_accum=self.grad_accum, workers_per_chip=m,
        )
        plan = make_batches(
            dataframe, self.features_col, self.label_col, self.batch_size,
            num_workers=engine.num_workers, window=self.communication_window,
            num_epoch=self.num_epoch, shuffle=shuffle, seed=self.seed, transform=self.transform,
        )
        state = self._execute(engine, plan)
        averaged = jax.tree.map(lambda a: jnp.mean(a, axis=0), state.locals_)
        self.record_training_stop()
        return self._finish_model(averaged, state,
                                  state_reduce=lambda a: jnp.mean(a, axis=0))


class EnsembleTrainer(DistributedTrainer):
    """Train N independent models, return all of them (reference
    ``EnsembleTrainer``)."""

    communication_window = _config_prop("communication_window")

    def __init__(self, *args, communication_window: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        self.config = self.config.replace(communication_window=communication_window)

    def train(self, dataframe: DataFrame, shuffle: bool = False) -> list[Model]:
        self.record_training_start()
        mesh, m = self._mesh()
        engine = AsyncEngine(
            self.model, self.worker_optimizer, self.loss, EnsembleFold(), mesh,
            window=self.communication_window, learning_rate=self.learning_rate,
            compute_dtype=self.compute_dtype, seed=self.seed, per_worker_init=True,
            grad_accum=self.grad_accum, workers_per_chip=m,
        )
        plan = make_batches(
            dataframe, self.features_col, self.label_col, self.batch_size,
            num_workers=engine.num_workers, window=self.communication_window,
            num_epoch=self.num_epoch, shuffle=shuffle, seed=self.seed, transform=self.transform,
        )
        state = self._execute(engine, plan)
        self.record_training_stop()
        stacked = jax.device_get(state.locals_)
        models = []
        for i in range(engine.num_workers):
            params_i = jax.tree.map(lambda a: a[i], stacked)
            models.append(self._finish_model(params_i, state, worker=i))
        return models
