"""Training-aware telemetry: discipline staleness gauges + straggler flags.

``parallel/disciplines.py`` computes per-worker staleness *inside* the jitted
fold (DynSGD's rotating ``(worker_id + round) mod W`` schedule) where no host
observer can see it. This module re-derives the same deterministic schedule
host-side — the rotation is a pure function of (round, W), so no device value
need be fetched — and surfaces it as gauges plus per-round record fields,
alongside the per-worker loss divergence every async engine's replicated
``[W]`` loss vector already carries.

The straggler heuristic is deliberately simple and data-source-agnostic:
``time > k * median(times)`` over whatever round/worker times the caller has
(live per-round wall times here; per-worker times from a multihost trace or
the report CLI's JSONL replay).
"""

from __future__ import annotations

import bisect
import collections
from typing import Optional, Sequence

import numpy as np

#: default straggler threshold: flag times above k x median.
STRAGGLER_K = 2.0


def flag_stragglers(times: Sequence[float],
                    k: float = STRAGGLER_K) -> list[int]:
    """Indices whose time exceeds ``k`` x the median of ``times``.

    With fewer than 3 samples the median is too weak an anchor — nothing is
    flagged rather than flagging half of a pair.
    """
    times = np.asarray(list(times), dtype=np.float64)
    if times.size < 3:
        return []
    med = float(np.median(times))
    if med <= 0.0:
        return []
    return [int(i) for i in np.flatnonzero(times > k * med)]


def staleness_schedule(discipline, round_idx: int,
                       num_workers: int) -> Optional[np.ndarray]:
    """Per-worker staleness at ``round_idx`` under the serialized-commit
    model, or None for disciplines where staleness is not defined.

    Matches ``disciplines.py`` exactly: commits within a round serialize in
    rotated worker order, so worker ``i``'s commit lands after
    ``(i + round) mod W`` fresher commits. Only DynSGD *folds* by it, but the
    schedule (and therefore the gauge) applies to every communicating
    discipline — they all share the serialized-commit semantics.
    """
    communicates = getattr(discipline, "communicates", False)
    if not communicates or num_workers < 1:
        return None
    w = np.arange(num_workers)
    return ((w + round_idx) % num_workers).astype(np.float64)


def dynsgd_scales(staleness: np.ndarray) -> np.ndarray:
    """DynSGD's fold scale per worker: ``1 / (staleness + 1)`` — the exact
    expression in ``DynSGDFold.commit``."""
    return 1.0 / (staleness + 1.0)


class DisciplineMonitor:
    """Per-round observer for an async engine's discipline.

    ``round_fields(r, loss)`` returns the discipline-aware fields a round
    record should carry; gauges land in the given telemetry registry as a
    side effect. Constructed by ``Trainer._execute`` when the engine exposes
    a discipline (sync engines have no staleness — the monitor is inert for
    them except loss divergence when a ``[W]`` loss arrives).
    """

    #: straggler-median window size: recent rounds only, bounding per-round
    #: cost and keeping the anchor current on long runs.
    MEDIAN_WINDOW = 512

    def __init__(self, discipline=None, num_workers: int = 1, telemetry=None):
        from distkeras_tpu import telemetry as _t

        self.discipline = discipline
        self.num_workers = int(num_workers)
        self.telemetry = telemetry if telemetry is not None else _t.get()
        self._is_dynsgd = type(discipline).__name__ == "DynSGDFold"
        #: running-median anchor for live straggler flagging (rounds, not
        #: workers: per-worker times don't exist inside one fused XLA
        #: program). Bounded window: an unbounded sorted list would cost
        #: O(n) memmove per round forever and anchor on a lifetime median;
        #: the deque tracks insertion order for eviction, ``_times`` stays
        #: sorted for the median.
        self._window = collections.deque(maxlen=self.MEDIAN_WINDOW)
        self._times: list[float] = []

    def round_fields(self, round_idx: int, loss,
                     round_seconds: Optional[float] = None) -> dict:
        fields: dict = {}
        tele = self.telemetry
        stale = staleness_schedule(self.discipline, round_idx,
                                   self.num_workers)
        if stale is not None and self.num_workers > 1:
            fields["staleness"] = [int(s) for s in stale]
            tele.gauge("discipline.staleness_mean").set(float(stale.mean()))
            tele.gauge("discipline.staleness_max").set(float(stale.max()))
            if self._is_dynsgd:
                scales = dynsgd_scales(stale)
                fields["dynsgd_scale"] = [round(float(s), 6) for s in scales]
                tele.gauge("discipline.dynsgd_scale_min").set(
                    float(scales.min()))
        loss = np.asarray(loss)
        if loss.size > 1:
            div = loss.astype(np.float64).ravel() - float(loss.mean())
            fields["loss_divergence"] = [round(float(d), 6) for d in div]
            tele.gauge("discipline.loss_divergence_max").set(
                float(np.abs(div).max()))
        # Callers pass round_seconds=None for burst-tail callbacks (interior
        # rounds of a compiled block — MetricsLogger derives this from the
        # engine's state contract): tails must neither anchor the median nor
        # be flagged, or every real block would read as a straggler against
        # a tail-scale median. Real boundaries count however fast they are.
        if round_seconds is not None and round_seconds > 0:
            if len(self._window) == self._window.maxlen:
                evicted = self._window[0]
                del self._times[bisect.bisect_left(self._times, evicted)]
            self._window.append(round_seconds)
            bisect.insort(self._times, round_seconds)
            n = len(self._times)
            med = self._times[n // 2] if n % 2 else 0.5 * (
                self._times[n // 2 - 1] + self._times[n // 2])
            if n >= 3 and med > 0 and round_seconds > STRAGGLER_K * med:
                fields["straggler"] = True
                tele.counter("discipline.straggler_rounds").add(1)
        return fields
