"""Telemetry primitives: spans, counters, gauges, histograms.

One process-wide :class:`Telemetry` registry aggregates everything the
trainer, engines, data plane, and predictors observe (the reference recorded
wall-clock only — ``Trainer.record_training_start/stop``; SURVEY.md §5).
Design constraints, in order:

* **Low overhead.** A span is two ``perf_counter`` calls plus one locked
  histogram update (~1-2 µs); hot paths (a fold round, a native gather) are
  hundreds of µs to ms. ``DKTPU_TELEMETRY=0`` swaps in no-op singletons so
  even that cost vanishes.
* **Thread-safe.** The RoundFeeder stages batches on its own thread and the
  consumer loop observes from the main thread; every metric guards its state
  with one lock. Span nesting is tracked per-thread (``threading.local``).
* **Pure host-side.** No jax imports, no device work, no fences — telemetry
  must never perturb the async dispatch pipeline it measures.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Optional

#: log2-spaced histogram boundaries (seconds): ~1 µs .. 64 s. Fixed buckets
#: keep ``observe`` O(log n) with no allocation, and export directly as
#: Prometheus ``le`` buckets.
BUCKET_BOUNDS = tuple(2.0 ** e for e in range(-20, 7))

#: round timings under this are burst-tail callbacks, not real timing
#: boundaries (blocked/auto execution delivers one callback burst per
#: compiled block; tail callbacks arrive ~µs apart while a real round
#: includes at least a JSONL write). The ONE home for the constant —
#: MetricsLogger segmentation, the live straggler monitor, and the offline
#: report must all agree or they silently diverge.
BURST_EPS_S = 1e-4


class Counter:
    """Monotonic counter (adds only)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> float:
        return self._value


class Gauge:
    """Last-value gauge that also tracks min/max/mean over its lifetime."""

    __slots__ = ("name", "_value", "_count", "_total", "_min", "_max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._count = 0
        self._total = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._value = v
            self._count += 1
            self._total += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        with self._lock:
            if not self._count:
                return {"value": 0.0, "count": 0}
            return {
                "value": self._value,
                "count": self._count,
                "mean": self._total / self._count,
                "min": self._min,
                "max": self._max,
            }


class Histogram:
    """Fixed-bucket latency histogram (seconds) with sum/count/min/max."""

    __slots__ = ("name", "_counts", "_count", "_total", "_min", "_max",
                 "_lock")

    def __init__(self, name: str):
        self.name = name
        self._counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self._count = 0
        self._total = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(BUCKET_BOUNDS, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._total += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return self._total

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper bound of the target bucket)."""
        with self._lock:
            if not self._count:
                return 0.0
            target = q * self._count
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if seen >= target and c:
                    return (BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS)
                            else self._max)
            return self._max

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "count": self._count,
                "total": self._total,
                "buckets": list(self._counts),
            }
            if self._count:
                snap.update(
                    mean=self._total / self._count,
                    min=self._min,
                    max=self._max,
                )
            return snap


# -- attribution labels (per-thread, ambient) -------------------------------
#: thread-local stack of label dicts pushed by :func:`scoped_labels`.
_LABELS = threading.local()

#: metric-name suffix order: the tenant owns the job, so the tenant comes
#: first — `fleet.commits.<tenant>.<job>` groups by tenant in sorted dumps.
_LABEL_ORDER = ("tenant", "job")

#: label values ride inside dotted metric names, so they must stay single
#: dot-free tokens; anything else is flattened to `-`.
_LABEL_SANITIZE = re.compile(r"[^0-9A-Za-z_-]+")


def sanitize_label(value) -> str:
    """One metric-name-safe token for a label value (dots and whitespace
    become ``-``; empty values read ``unknown``)."""
    return _LABEL_SANITIZE.sub("-", str(value)).strip("-") or "unknown"


def current_labels() -> dict:
    """The merged ambient label dict for this thread (innermost scope
    wins), ``{}`` when no scope is active."""
    stack = getattr(_LABELS, "stack", None)
    if not stack:
        return {}
    merged: dict = {}
    for d in stack:
        merged.update(d)
    return merged


def label_suffix() -> str:
    """The ambient labels as a metric-name suffix: ``.<tenant>.<job>``
    (sanitized, tenant first), ``""`` when no scope is active — so
    instrumented code can write ``counter("fleet.commits" +
    label_suffix())`` and stay label-free outside a fleet run."""
    labels = current_labels()
    parts = [sanitize_label(labels[k]) for k in _LABEL_ORDER if k in labels]
    return ("." + ".".join(parts)) if parts else ""


class _LabelScope:
    """Context manager pushing one label dict onto the thread's stack.
    Events recorded inside the scope carry the labels automatically
    (:meth:`Telemetry.event` merges them under any explicit fields)."""

    __slots__ = ("_labels",)

    def __init__(self, labels: dict):
        self._labels = labels

    def __enter__(self) -> "_LabelScope":
        stack = getattr(_LABELS, "stack", None)
        if stack is None:
            stack = _LABELS.stack = []
        stack.append(self._labels)
        return self

    def __exit__(self, *exc) -> None:
        stack = getattr(_LABELS, "stack", None)
        if stack and stack[-1] is self._labels:
            stack.pop()
        return None


def scoped_labels(**labels) -> _LabelScope:
    """Attach attribution labels (``tenant=``, ``job=``, ...) to this
    thread for the scope's duration. The fleet scheduler wraps every
    worker thread in one, so per-job metrics and every event fired under
    it (supervisor retries, host restarts, evictions) are attributable
    to a tenant without threading arguments through each call site."""
    return _LabelScope(dict(labels))


class _SpanContext:
    """Context manager recording one timed span into the registry.

    Nesting builds a per-thread dotted path: ``span("round")`` containing
    ``span("dispatch")`` records under ``round`` and ``round/dispatch``.
    """

    __slots__ = ("_tele", "_name", "_t0", "_path")

    def __init__(self, tele: "Telemetry", name: str):
        self._tele = tele
        self._name = name
        self._t0 = 0.0
        self._path = name

    def __enter__(self) -> "_SpanContext":
        stack = self._tele._span_stack()
        self._path = (stack[-1] + "/" + self._name) if stack else self._name
        stack.append(self._path)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        stack = self._tele._span_stack()
        if stack and stack[-1] == self._path:
            stack.pop()
        self._tele.histogram(self._path).observe(dt)
        return None


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NOOP_SPAN = _NoopSpan()


class _NoopMetric:
    """Shared do-nothing stand-in for Counter/Gauge/Histogram when disabled."""

    __slots__ = ()
    name = "noop"
    value = 0.0
    count = 0
    total = 0.0

    def add(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def quantile(self, q: float) -> float:
        return 0.0

    def snapshot(self):
        return {}


_NOOP_METRIC = _NoopMetric()


#: optional observer of every recorded event (the tracing flight ring
#: registers here at import) — a plain callable taking the event dict.
#: Core stays import-clean: it never imports tracing; tracing plugs in.
_EVENT_TAP = None


def set_event_tap(tap) -> None:
    """Install (or clear, with None) the process-wide event observer."""
    global _EVENT_TAP
    _EVENT_TAP = tap


class Telemetry:
    """Per-process metric registry: named spans, counters, gauges, histograms.

    ``enabled=False`` (or env ``DKTPU_TELEMETRY=0`` for the ambient registry)
    turns every accessor into a no-op — instrumented code needs no branches.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self._local = threading.local()
        self._events: list[dict] = []

    # -- span nesting ------------------------------------------------------
    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str):
        """Timed context manager; nested spans record under ``parent/child``."""
        if not self.enabled:
            return _NOOP_SPAN
        return _SpanContext(self, name)

    # -- metric accessors (create-on-first-use) ----------------------------
    def counter(self, name: str) -> Counter:
        if not self.enabled:
            return _NOOP_METRIC
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        if not self.enabled:
            return _NOOP_METRIC
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> Histogram:
        if not self.enabled:
            return _NOOP_METRIC
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def event(self, kind: str, fields: Optional[dict] = None) -> None:
        """Record a discrete event (kept in memory; written by the JSONL
        exporter). Use sparingly — one per round is fine, one per sample is
        not."""
        if not self.enabled:
            return
        rec = {"kind": kind, "ts": time.time()}
        # Ambient attribution labels ride under the explicit fields: an
        # event fired inside a fleet worker scope names its tenant/job
        # without the call site knowing the scope exists.
        rec.update(current_labels())
        if fields:
            rec.update(fields)
        with self._lock:
            self._events.append(rec)
        tap = _EVENT_TAP
        if tap is not None:
            tap(rec)

    # -- export ------------------------------------------------------------
    def snapshot(self) -> dict:
        """One JSON-serializable summary of every aggregate."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        return {
            "counters": {n: c.snapshot() for n, c in counters.items()},
            "gauges": {n: g.snapshot() for n, g in gauges.items()},
            "spans": {n: h.snapshot() for n, h in hists.items()},
        }

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    # -- windows (per-run accounting on the shared registry) ----------------
    def mark(self) -> dict:
        """Opaque position marker for :meth:`delta` — take one at run start
        to report only that run's activity from the process-global registry
        (sequential trainer runs share it; without a window, run 2's dump
        would re-attribute run 1's counters, spans, and events)."""
        with self._lock:
            n_events = len(self._events)
        return {"snapshot": self.snapshot(), "events": n_events}

    def delta(self, mark: dict) -> tuple[dict, list]:
        """(summary, events) accumulated since ``mark``.

        Counters and histogram count/total/buckets subtract exactly; a
        window has no well-defined min/max, so histogram deltas carry
        count/total/mean/buckets only. Gauges are level signals — the
        current snapshot is reported for any gauge touched in the window.
        """
        before = mark["snapshot"]
        after = self.snapshot()
        counters = {}
        for name, v in after["counters"].items():
            dv = v - before["counters"].get(name, 0.0)
            if dv:
                counters[name] = dv
        gauges = {
            name: g for name, g in after["gauges"].items()
            if g.get("count", 0) > before["gauges"].get(name, {}).get(
                "count", 0)
        }
        spans = {}
        for name, h in after["spans"].items():
            prev = before["spans"].get(name,
                                       {"count": 0, "total": 0.0,
                                        "buckets": []})
            dc = h["count"] - prev["count"]
            if dc <= 0:
                continue
            dt = h["total"] - prev["total"]
            pb = prev["buckets"] or [0] * len(h["buckets"])
            spans[name] = {
                "count": dc,
                "total": dt,
                "mean": dt / dc,
                "buckets": [a - b for a, b in zip(h["buckets"], pb)],
            }
        with self._lock:
            events = list(self._events[mark["events"]:])
        return ({"counters": counters, "gauges": gauges, "spans": spans},
                events)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._events.clear()


# -- ambient (process-global) registry ------------------------------------
_GLOBAL: Optional[Telemetry] = None
_GLOBAL_LOCK = threading.Lock()


def enabled() -> bool:
    from distkeras_tpu.runtime import config  # jax-free module: safe here

    return config.env_bool("DKTPU_TELEMETRY")


def get() -> Telemetry:
    """The process-global registry (respects ``DKTPU_TELEMETRY=0``)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = Telemetry(enabled=enabled())
    return _GLOBAL


def reset() -> None:
    """Clear the global registry (tests; between bench configs)."""
    get().reset()
