"""Run-report renderer: ``python -m distkeras_tpu.telemetry report run.jsonl``.

Reads a JSONL produced by ``MetricsLogger`` (per-round records + the
telemetry-summary record its ``close()`` appends) or by
``telemetry.exporters.write_jsonl`` directly, and renders:

* per-phase time breakdown (span totals, counts, means, share of the run);
* throughput segments (the same burst-grouping ``MetricsLogger`` uses, so
  blocked/auto runs report per-segment rates, not burst-tail garbage);
* staleness summary (per-worker staleness distribution, DynSGD scales,
  per-worker loss divergence) from discipline-aware round fields;
* a straggler table: rounds whose wall time exceeds ``k`` x the median (and
  any record-time ``straggler`` flags the live monitor set).
"""

from __future__ import annotations

import io
from typing import Optional

import numpy as np

from distkeras_tpu.telemetry.core import BURST_EPS_S
from distkeras_tpu.telemetry.exporters import SUMMARY_KIND, read_jsonl
from distkeras_tpu.telemetry.training import STRAGGLER_K, flag_stragglers


def _round_records(records: list[dict]) -> list[dict]:
    return [r for r in records if "round" in r and "kind" not in r]


def _summaries(records: list[dict]) -> list[dict]:
    return [r for r in records if r.get("kind") == SUMMARY_KIND]


def _is_burst_tail(r: dict) -> bool:
    """Interior round of a compiled block — not a timing boundary. The
    explicit ``burst_tail`` marker (written by MetricsLogger from the
    engine's state=None contract) is authoritative; the dt threshold is the
    fallback for records that predate it."""
    return bool(r.get("burst_tail",
                      (r.get("round_seconds") or 0.0) < BURST_EPS_S))


def throughput_segments(rounds: list[dict]) -> list[dict]:
    """Burst-grouped throughput segments (rounds, seconds, samples/s)."""
    segments: list[dict] = []
    for r in rounds:
        dt = r.get("round_seconds")
        if dt is None:
            continue
        sps = r.get("samples_per_sec")
        spr = sps * dt if sps else 0.0
        if segments and _is_burst_tail(r):
            segments[-1]["rounds"] += 1
            segments[-1]["seconds"] += dt
            segments[-1]["samples"] += spr
        else:
            segments.append(
                {"rounds": 1, "seconds": dt, "samples": spr,
                 "first_round": r["round"]})
    for s in segments:
        s["samples_per_sec"] = (s["samples"] / s["seconds"]
                                if s["seconds"] > 0 else 0.0)
    return segments


def _hist_max(h: dict) -> float:
    """Exact max when present; otherwise the upper bound of the highest
    occupied bucket (windowed summaries from ``Telemetry.delta`` carry
    count/total/mean/buckets only — a window has no well-defined min/max)."""
    if "max" in h:
        return h["max"]
    from distkeras_tpu.telemetry.core import BUCKET_BOUNDS

    buckets = h.get("buckets", [])
    for i in range(len(buckets) - 1, -1, -1):
        if buckets[i]:
            return (BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS)
                    else float("inf"))
    return 0.0


def phase_breakdown(summary: dict) -> list[dict]:
    """Span aggregates sorted by total time, with share of the longest
    top-level span (the closest thing a JSONL has to 'the run')."""
    spans = summary.get("spans", {})
    rows = []
    top_total = max(
        (h.get("total", 0.0) for n, h in spans.items() if "/" not in n),
        default=0.0)
    for name, h in spans.items():
        total = h.get("total", 0.0)
        rows.append({
            "span": name,
            "count": h.get("count", 0),
            "total_s": total,
            "mean_s": h.get("mean", 0.0),
            "max_s": _hist_max(h),
            "share": (total / top_total) if top_total > 0 else None,
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def staleness_summary(rounds: list[dict]) -> Optional[dict]:
    """Aggregate the discipline-aware per-round fields, if any."""
    stale_rows = [r["staleness"] for r in rounds if "staleness" in r]
    out: dict = {}
    if stale_rows:
        mat = np.asarray(stale_rows, dtype=np.float64)  # [rounds, W]
        out["num_workers"] = mat.shape[1]
        out["per_worker_mean"] = [round(float(v), 3) for v in mat.mean(0)]
        out["per_worker_max"] = [int(v) for v in mat.max(0)]
    scales = [r["dynsgd_scale"] for r in rounds if "dynsgd_scale" in r]
    if scales:
        mat = np.asarray(scales, dtype=np.float64)
        out["dynsgd_scale_mean"] = [round(float(v), 4) for v in mat.mean(0)]
    divs = [r["loss_divergence"] for r in rounds if "loss_divergence" in r]
    if divs:
        mat = np.asarray(divs, dtype=np.float64)
        out["loss_divergence_rms"] = [
            round(float(v), 6) for v in np.sqrt((mat ** 2).mean(0))]
        out["loss_divergence_max_abs"] = round(float(np.abs(mat).max()), 6)
    return out or None


#: metric names recognized inside ``fleet.<metric>.<tenant>.<job>``
#: counter/gauge/span names (the fleet scheduler's attribution
#: convention — tenant and job are sanitized to dot-free tokens, so a
#: 4-way split is unambiguous).
_FLEET_METRICS = frozenset({
    "commits", "preemptions", "shrinks", "expands", "restarts",
    "placements", "preempt_debt", "granted", "staleness_mean",
    "staleness_max", "round",
})


def fleet_attribution(summary: dict) -> list[dict]:
    """Per-(tenant, job) rollup of the fleet scheduler's labeled metrics:
    throughput (commits + round-span wall time), staleness, restarts, and
    preemption accounting — one row per job, tenants grouped."""
    jobs: dict = {}

    def row(tenant: str, job: str) -> dict:
        return jobs.setdefault((tenant, job), {"tenant": tenant, "job": job})

    for name, v in summary.get("counters", {}).items():
        parts = name.split(".")
        if (len(parts) == 4 and parts[0] == "fleet"
                and parts[1] in _FLEET_METRICS):
            row(parts[2], parts[3])[parts[1]] = v
    for name, g in summary.get("gauges", {}).items():
        parts = name.split(".")
        if (len(parts) == 4 and parts[0] == "fleet"
                and parts[1] in _FLEET_METRICS):
            row(parts[2], parts[3])[parts[1]] = g.get("value")
    for name, h in summary.get("spans", {}).items():
        parts = name.split(".")
        if (len(parts) == 4 and parts[0] == "fleet" and parts[1] == "round"):
            r = row(parts[2], parts[3])
            r["round_total_s"] = h.get("total", 0.0)
            r["round_mean_s"] = h.get("mean", 0.0)
            total = h.get("total", 0.0)
            if total > 0:
                # Throughput = COMMITTED rounds over round wall time; the
                # span count would also bill evicted/requeued attempts,
                # overstating c/s exactly when preemption churn occurs.
                commits = r.get("commits", h.get("count", 0))
                r["commits_per_sec"] = round(commits / total, 3)
    return [jobs[k] for k in sorted(jobs)]


def _hist_quantile(h: dict, q: float) -> float:
    """Bucket-resolution quantile from a histogram *snapshot* (the same
    walk as ``Histogram.quantile``, but over the serialized form a JSONL
    summary carries)."""
    from distkeras_tpu.telemetry.core import BUCKET_BOUNDS

    buckets = h.get("buckets", [])
    count = h.get("count", 0)
    if not count:
        return 0.0
    target = q * count
    seen = 0
    for i, c in enumerate(buckets):
        seen += c
        if seen >= target and c:
            return (BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS)
                    else _hist_max(h))
    return _hist_max(h)


#: ``serving.*`` counter names surfaced in the Serving report section —
#: the request-accounting vocabulary from ``distkeras_tpu/serving/``.
_SERVING_COUNTERS = (
    "accepted", "answered", "shed", "deadline_drops", "batches",
    "batched_rows", "padded_rows", "swaps", "swap_failures",
    "retrace_after_warmup", "client_failovers", "conn_errors",
)


def serving_summary(summary: dict) -> Optional[dict]:
    """Roll up the serving plane's metrics: request accounting (accepted /
    answered / shed — the shed-before-accept contract is checkable right
    here), latency quantiles from the ``serving.latency`` histogram, batch
    padding overhead, and hot-swap counts. None when the run served
    nothing."""
    out: dict = {}
    for name in _SERVING_COUNTERS:
        v = summary.get("counters", {}).get(f"serving.{name}")
        if v is not None:
            out[name] = v
    lat = summary.get("spans", {}).get("serving.latency")
    if lat and lat.get("count"):
        out["latency_count"] = lat["count"]
        out["latency_mean_s"] = lat.get("mean",
                                        lat.get("total", 0.0) / lat["count"])
        out["latency_p50_s"] = _hist_quantile(lat, 0.50)
        out["latency_p99_s"] = _hist_quantile(lat, 0.99)
        out["latency_max_s"] = _hist_max(lat)
    depth = summary.get("gauges", {}).get("serving.queue_depth")
    if depth is not None:
        out["queue_depth_last"] = depth.get("value")
        out["queue_depth_max"] = depth.get("max")
    return out or None


def _sum_prefixed(table: dict, base: str) -> Optional[float]:
    """Sum ``base`` plus every tenant-suffixed variant (``base.<tenant>.
    <job>``) — streaming metrics carry the fleet label suffix when the
    trainer runs as a tenant."""
    total, found = 0.0, False
    for name, v in table.items():
        if name == base or name.startswith(base + "."):
            total += float(v)
            found = True
    return total if found else None


#: ``stream.*`` counters surfaced in the Streaming report section.
_STREAMING_COUNTERS = (
    ("stream.items_read", "items_read"),
    ("stream.items_committed", "items_committed"),
    ("stream.requeued", "requeued"),
    ("stream.drift_events", "drift_events"),
    ("stream.drift_injected", "drift_injected"),
    ("stream.source_reconnects", "source_reconnects"),
)


def streaming_summary(summary: dict) -> Optional[dict]:
    """Roll up the streaming loop's metrics: ingest accounting (read /
    committed / requeued), offset lag, drift detections and recovery
    time, the windowed-eval means, and event-to-served-weight freshness
    quantiles from the ``serving.freshness`` histogram (recorded at each
    hot-swap). None when the run streamed nothing."""
    out: dict = {}
    counters = summary.get("counters", {})
    gauges = summary.get("gauges", {})
    for base, key in _STREAMING_COUNTERS:
        v = _sum_prefixed(counters, base)
        if v is not None:
            out[key] = v
    lag = gauges.get("stream.offset_lag")
    if lag is not None:
        out["offset_lag_last"] = lag.get("value")
        out["offset_lag_max"] = lag.get("max")
    for name, key in (("stream.recovery_seconds", "recovery_s"),
                      ("stream.eval.loss_fast", "eval_loss_fast"),
                      ("stream.eval.loss_slow", "eval_loss_slow"),
                      ("stream.candidate_loss", "candidate_loss")):
        g = gauges.get(name)
        if g is not None:
            out[key] = g.get("value")
    stale = [g.get("value") for n, g in gauges.items()
             if n == "stream.staleness_mean"
             or n.startswith("stream.staleness_mean.")]
    stale = [s for s in stale if s is not None]
    if stale:
        out["staleness_mean"] = max(stale)
    fresh = summary.get("spans", {}).get("serving.freshness")
    if fresh and fresh.get("count"):
        out["freshness_count"] = fresh["count"]
        out["freshness_p50_s"] = _hist_quantile(fresh, 0.50)
        out["freshness_p99_s"] = _hist_quantile(fresh, 0.99)
        out["freshness_max_s"] = _hist_max(fresh)
    rejected = counters.get("serving.swap_rejected_regression")
    if rejected is not None:
        out["swaps_rejected_regression"] = rejected
    return out or None


def shard_summary(summary: dict) -> Optional[dict]:
    """Roll up the sharded center plane's metrics: per-shard fold/byte
    counters (``netps.shard.folds.<k>`` / ``netps.shard.bytes.<k>``), the
    shard count and plan byte skew gauges, and the partial-commit count —
    the balance evidence for a partition plan lives right here (a skew
    near 1.0 and near-equal fold columns mean the byte-balancer did its
    job). None when the run had no sharded center."""
    counters = summary.get("counters", {})
    gauges = summary.get("gauges", {})
    folds: dict = {}
    nbytes: dict = {}
    for name, v in counters.items():
        parts = name.split(".")
        if len(parts) == 4 and parts[:2] == ["netps", "shard"]:
            try:
                k = int(parts[3])
            except ValueError:
                continue
            if parts[2] == "folds":
                folds[k] = v
            elif parts[2] == "bytes":
                nbytes[k] = v
    out: dict = {}
    if folds:
        out["per_shard_folds"] = [folds.get(k, 0.0)
                                  for k in range(max(folds) + 1)]
    if nbytes:
        out["per_shard_bytes"] = [nbytes.get(k, 0.0)
                                  for k in range(max(nbytes) + 1)]
    count = gauges.get("netps.shard.count")
    if count is not None:
        out["shard_count"] = count.get("value")
    skew = gauges.get("netps.shard.skew")
    if skew is not None:
        out["plan_skew"] = skew.get("value")
    partial = counters.get("netps.shard.partial_commits")
    if partial is not None:
        out["partial_commits"] = partial
    return out or None


def tuner_summary(records: list[dict], summary: dict) -> Optional[dict]:
    """Roll up the self-tuning data plane's evidence: the decision log
    (``tuner_decision`` events — knob, from→to, the gauge that triggered
    it, the round it landed in), the join-time probe sweep
    (``tuner_probe``), oscillation fallbacks (``tuner_fallback``), and
    the converged dialect (``tuner_run_summary``). None when the run
    never had the controller aboard (``DKTPU_NET_AUTOTUNE`` off)."""
    counters = summary.get("counters", {})
    decisions = [
        {"knob": e.get("knob"), "from": e.get("from"), "to": e.get("to"),
         "trigger": e.get("trigger"), "round": e.get("round")}
        for e in records if e.get("kind") == "tuner_decision"]
    probes = [
        {"codec": e.get("codec"), "probes": e.get("probes"),
         "seconds": e.get("seconds"), "score": e.get("score")}
        for e in records if e.get("kind") == "tuner_probe"]
    fallbacks = [
        {"knob": e.get("knob"), "restored": e.get("restored"),
         "round": e.get("round"), "reason": e.get("reason")}
        for e in records if e.get("kind") == "tuner_fallback"]
    converged = None
    for e in records:
        if e.get("kind") == "tuner_run_summary":
            converged = {k: e.get(k) for k in
                         ("inflight", "codec", "shards", "transport",
                          "retunes", "fallbacks", "deferred")}
    out: dict = {}
    if decisions:
        out["decisions"] = decisions
    if probes:
        out["probes"] = probes
    if fallbacks:
        out["fallbacks"] = fallbacks
    if converged is not None:
        out["converged"] = converged
    for key, name in (("deferred", "tuner.deferred"),
                      ("floor_violations", "tuner.floor_violations"),
                      ("knob_warnings", "tuner.knob_warnings"),
                      ("expand_blocked", "tuner.expand_blocked")):
        if counters.get(name):
            out[key] = counters[name]
    return out or None


def straggler_table(rounds: list[dict], k: float = STRAGGLER_K) -> list[dict]:
    """Rounds whose wall time exceeds ``k`` x the median round time (plus
    any rounds the live monitor already flagged). Burst-tail rounds
    (interior rounds of a compiled block) are real rounds but not timing
    boundaries — they are excluded from both the median anchor and the
    flagging, or every block-final round would flag against a tail-scale
    median."""
    timed = [(r["round"], r["round_seconds"], bool(r.get("straggler")))
             for r in rounds
             if r.get("round_seconds") and not _is_burst_tail(r)]
    if not timed:
        return []
    times = [t for _, t, _ in timed]
    med = float(np.median(times))
    flagged = set(flag_stragglers(times, k))
    return [
        {"round": rd, "seconds": t,
         "x_median": round(t / med, 2) if med > 0 else None,
         "flagged_live": live}
        for i, (rd, t, live) in enumerate(timed)
        if i in flagged or live
    ]


def build_report(path: str, k: float = STRAGGLER_K) -> dict:
    """The full structured report for one JSONL file."""
    records = read_jsonl(path)
    rounds = _round_records(records)
    summaries = _summaries(records)
    # Later summaries supersede earlier ones span-by-span (a re-used path
    # accumulates one summary per run; the last run's registry is current).
    merged: dict = {"spans": {}, "counters": {}, "gauges": {}}
    for s in summaries:
        for key in merged:
            merged[key].update(s.get(key, {}))
    segments = throughput_segments(rounds)
    total_s = sum(s["seconds"] for s in segments)
    return {
        "path": path,
        "rounds": len(rounds),
        "total_round_seconds": total_s,
        "phases": phase_breakdown(merged),
        "counters": merged["counters"],
        "gauges": merged["gauges"],
        "segments": segments,
        "staleness": staleness_summary(rounds),
        "stragglers": straggler_table(rounds, k),
        "fleet": fleet_attribution(merged),
        "serving": serving_summary(merged),
        "streaming": streaming_summary(merged),
        "shards": shard_summary(merged),
        "tuner": tuner_summary(records, merged),
        "losses": [r["loss"] for r in rounds if "loss" in r],
    }


def _fmt_seconds(s: float) -> str:
    if s >= 1.0:
        return f"{s:.2f}s"
    if s >= 1e-3:
        return f"{s * 1e3:.2f}ms"
    return f"{s * 1e6:.0f}us"


def render_report(report: dict) -> str:
    """Human-readable text rendering of :func:`build_report` output."""
    out = io.StringIO()
    w = out.write
    w(f"# Telemetry report — {report['path']}\n")
    w(f"rounds: {report['rounds']}   "
      f"round wall time: {_fmt_seconds(report['total_round_seconds'])}\n")
    if report["losses"]:
        w(f"loss: first {report['losses'][0]:.4f}  "
          f"last {report['losses'][-1]:.4f}\n")

    if report["phases"]:
        w("\n## Phase breakdown (spans)\n")
        w(f"{'span':<40} {'count':>7} {'total':>10} {'mean':>10} "
          f"{'share':>6}\n")
        for p in report["phases"]:
            share = f"{p['share'] * 100:.0f}%" if p["share"] is not None else "-"
            w(f"{p['span']:<40} {p['count']:>7} "
              f"{_fmt_seconds(p['total_s']):>10} "
              f"{_fmt_seconds(p['mean_s']):>10} {share:>6}\n")

    if report["segments"]:
        w("\n## Throughput segments\n")
        w(f"{'first_round':>11} {'rounds':>7} {'seconds':>10} "
          f"{'samples/s':>12}\n")
        for s in report["segments"]:
            sps = (f"{s['samples_per_sec']:,.0f}"
                   if s["samples_per_sec"] else "-")
            w(f"{s['first_round']:>11} {s['rounds']:>7} "
              f"{s['seconds']:>10.4f} {sps:>12}\n")

    stall = report["counters"].get("input_stall_seconds")
    if stall is not None and report["total_round_seconds"] > 0:
        frac = stall / report["total_round_seconds"]
        w(f"\ninput stall: {_fmt_seconds(stall)} "
          f"({frac * 100:.1f}% of round wall time)\n")

    if report["staleness"]:
        st = report["staleness"]
        w("\n## Staleness\n")
        if "per_worker_mean" in st:
            w(f"workers: {st['num_workers']}\n")
            w(f"per-worker mean staleness: {st['per_worker_mean']}\n")
            w(f"per-worker max staleness:  {st['per_worker_max']}\n")
        if "dynsgd_scale_mean" in st:
            w(f"DynSGD mean fold scale:    {st['dynsgd_scale_mean']}\n")
        if "loss_divergence_rms" in st:
            w(f"loss divergence rms:       {st['loss_divergence_rms']}\n")
            w(f"loss divergence max |.|:   "
              f"{st['loss_divergence_max_abs']}\n")

    if report.get("fleet"):
        w("\n## Fleet (per-tenant attribution)\n")
        w(f"{'tenant':<12} {'job':<14} {'commits':>8} {'c/s':>7} "
          f"{'stale':>6} {'preempt':>8} {'shrink':>7} {'expand':>7} "
          f"{'restart':>8} {'debt':>5}\n")
        for r in report["fleet"]:
            cps = r.get("commits_per_sec")
            w(f"{r['tenant']:<12} {r['job']:<14} "
              f"{r.get('commits', 0):>8.0f} "
              f"{(f'{cps:.1f}' if cps is not None else '-'):>7} "
              f"{r.get('staleness_mean', 0.0):>6.2f} "
              f"{r.get('preemptions', 0):>8.0f} "
              f"{r.get('shrinks', 0):>7.0f} {r.get('expands', 0):>7.0f} "
              f"{r.get('restarts', 0):>8.0f} "
              f"{r.get('preempt_debt', 0.0):>5.0f}\n")

    if report.get("serving"):
        sv = report["serving"]
        w("\n## Serving\n")
        w(f"accepted: {sv.get('accepted', 0):.0f}   "
          f"answered: {sv.get('answered', 0):.0f}   "
          f"shed: {sv.get('shed', 0):.0f}   "
          f"deadline drops: {sv.get('deadline_drops', 0):.0f}\n")
        if "latency_count" in sv:
            w(f"latency: p50 {_fmt_seconds(sv['latency_p50_s'])}   "
              f"p99 {_fmt_seconds(sv['latency_p99_s'])}   "
              f"mean {_fmt_seconds(sv['latency_mean_s'])}   "
              f"max {_fmt_seconds(sv['latency_max_s'])}\n")
        if sv.get("batches"):
            rows = sv.get("batched_rows", 0)
            pad = sv.get("padded_rows", 0)
            frac = pad / (rows + pad) if (rows + pad) else 0.0
            w(f"batches: {sv['batches']:.0f}   rows: {rows:.0f}   "
              f"padding overhead: {frac * 100:.1f}%\n")
        w(f"hot-swaps: {sv.get('swaps', 0):.0f} "
          f"({sv.get('swap_failures', 0):.0f} rejected)   "
          f"retraces after warmup: "
          f"{sv.get('retrace_after_warmup', 0):.0f}\n")

    if report.get("streaming"):
        st = report["streaming"]
        w("\n## Streaming\n")
        w(f"items: read {st.get('items_read', 0):.0f}   "
          f"committed {st.get('items_committed', 0):.0f}   "
          f"requeued {st.get('requeued', 0):.0f}\n")
        if "offset_lag_last" in st:
            w(f"offset lag: last {st['offset_lag_last']:.0f}   "
              f"max {st.get('offset_lag_max', 0):.0f}\n")
        if st.get("drift_events") is not None or \
                st.get("drift_injected") is not None:
            w(f"drift: detected {st.get('drift_events', 0):.0f}   "
              f"injected {st.get('drift_injected', 0):.0f}")
            if st.get("recovery_s") is not None:
                w(f"   last recovery {_fmt_seconds(st['recovery_s'])}")
            w("\n")
        if st.get("eval_loss_fast") is not None:
            w(f"windowed eval loss: fast {st['eval_loss_fast']:.4f}   "
              f"slow {st.get('eval_loss_slow', float('nan')):.4f}\n")
        if "freshness_count" in st:
            w(f"event-to-served-weight freshness: "
              f"p50 {_fmt_seconds(st['freshness_p50_s'])}   "
              f"p99 {_fmt_seconds(st['freshness_p99_s'])}   "
              f"max {_fmt_seconds(st['freshness_max_s'])} "
              f"({st['freshness_count']:.0f} swaps)\n")
        for key, label in (("source_reconnects", "source reconnects"),
                           ("swaps_rejected_regression",
                            "swaps rejected (regression)")):
            if st.get(key):
                w(f"{label}: {st[key]:.0f}\n")
        if st.get("staleness_mean") is not None:
            w(f"staleness mean: {st['staleness_mean']:.2f}\n")

    if report.get("shards"):
        sh = report["shards"]
        w("\n## Sharded center\n")
        if "shard_count" in sh:
            skew = sh.get("plan_skew")
            w(f"shards: {sh['shard_count']:.0f}   plan byte skew: "
              f"{(f'{skew:.3f}' if skew is not None else '-')}\n")
        if sh.get("per_shard_folds"):
            w(f"per-shard folds: "
              f"{[int(v) for v in sh['per_shard_folds']]}\n")
        if sh.get("per_shard_bytes"):
            w(f"per-shard bytes: "
              f"{[int(v) for v in sh['per_shard_bytes']]}\n")
        if sh.get("partial_commits"):
            w(f"partial commits (reconciled): "
              f"{sh['partial_commits']:.0f}\n")

    if report.get("tuner"):
        tu = report["tuner"]
        w("\n## Tuner\n")
        conv = tu.get("converged")
        if conv:
            w(f"converged: codec={conv.get('codec')} "
              f"inflight={conv.get('inflight')} "
              f"shards={conv.get('shards')} "
              f"transport={conv.get('transport')}   "
              f"retunes: {conv.get('retunes', 0)}   "
              f"fallbacks: {conv.get('fallbacks', 0)}   "
              f"deferred: {conv.get('deferred', 0)}\n")
        if tu.get("probes"):
            w(f"{'probe codec':<12} {'probes':>7} {'seconds':>10} "
              f"{'bytes/s':>14}\n")
            for p in tu["probes"]:
                w(f"{str(p['codec']):<12} {p['probes']:>7} "
                  f"{p['seconds']:>10.4f} {p['score']:>14,.0f}\n")
        if tu.get("decisions"):
            w(f"{'round':>7} {'knob':<12} {'from':>8} {'to':>8} "
              f"trigger\n")
            for d in tu["decisions"]:
                w(f"{d['round']:>7} {str(d['knob']):<12} "
                  f"{str(d['from']):>8} {str(d['to']):>8} "
                  f"{d['trigger']}\n")
        for fb in tu.get("fallbacks", ()):
            w(f"oscillation fallback: {fb['knob']} restored to "
              f"{fb['restored']} at round {fb['round']} ({fb['reason']})\n")
        for key, label in (("floor_violations", "floor violations"),
                           ("knob_warnings", "knob warnings"),
                           ("expand_blocked", "expansions blocked"),
                           ("deferred", "deferred applies")):
            if tu.get(key):
                w(f"{label}: {tu[key]:.0f}\n")

    w("\n## Stragglers\n")
    if report["stragglers"]:
        w(f"{'round':>7} {'seconds':>10} {'x median':>9} {'live flag':>10}\n")
        for s in report["stragglers"]:
            w(f"{s['round']:>7} {s['seconds']:>10.4f} "
              f"{s['x_median']:>9} {str(s['flagged_live']):>10}\n")
    else:
        w("none flagged\n")
    return out.getvalue()


def merged_records(path: str) -> list[dict]:
    """The clock-aligned, deduped record list for ``path``: a directory
    is collector-merged across every per-process stream (+ rotated
    generations) it holds; a single file goes through the same collector
    so one-stream and N-stream paths render identically."""
    import os

    from distkeras_tpu.telemetry.tracing import TelemetryCollector

    if os.path.isdir(path):
        return TelemetryCollector.from_dir(path).records()
    return TelemetryCollector([path]).records()


def scrape_stats(endpoint: str, ring: int = 64,
                 timeout: float = 5.0) -> dict:
    """One live ``stats`` frame from a PS/serving process: counters,
    gauges, and the head of its flight-recorder ring — no join, no
    membership, works against a standby or a fenced ex-primary (the
    processes a postmortem most wants to ask)."""
    import socket

    from distkeras_tpu.netps import wire

    host, port = wire.split_endpoint(endpoint)
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(timeout)
        wire.send_frame(sock, wire.KIND_REQUEST,
                        {"op": wire.OP_STATS, "req": 0,
                         "ring": int(ring)}, [])
        while True:
            kind, rhdr, _arrays = wire.read_frame(sock)
            if kind == wire.KIND_REPLY and rhdr.get("req") == 0:
                return rhdr


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.telemetry",
        description="Render a run report from a metrics/telemetry JSONL.")
    sub = parser.add_subparsers(dest="command", required=True)
    rep = sub.add_parser("report", help="render a per-run report")
    rep.add_argument("path", help="metrics/telemetry JSONL file (or, with "
                                  "--trace, a directory of per-process "
                                  "streams to collector-merge)")
    rep.add_argument("--straggler-k", type=float, default=STRAGGLER_K,
                     help="flag rounds slower than k x median "
                          f"(default {STRAGGLER_K})")
    rep.add_argument("--trace", action="store_true",
                     help="render the distributed-trace report (critical-"
                          "path breakdown, completeness, chaos "
                          "correlation) instead of the run report")
    rep.add_argument("--json", action="store_true",
                     help="emit the structured report as JSON instead of text")
    scr = sub.add_parser(
        "scrape", help="fetch a live telemetry snapshot from a running "
                       "PS/serving process over the wire")
    scr.add_argument("endpoint", help="host:port of the process to scrape")
    scr.add_argument("--ring", type=int, default=64,
                     help="flight-ring records to include (default 64)")
    scr.add_argument("--timeout", type=float, default=5.0)
    scr.add_argument("--json", action="store_true",
                     help="one compact JSON line (scripts/pipelines) "
                          "instead of the indented dump")
    from distkeras_tpu.telemetry.health import cli as health_cli

    health_cli.add_subcommands(sub)
    args = parser.parse_args(argv)
    if args.command == "health":
        return health_cli.cmd_health(args)
    if args.command == "top":
        return health_cli.cmd_top(args)
    if args.command == "scrape":
        import socket
        import sys

        try:
            stats = scrape_stats(args.endpoint, ring=args.ring,
                                 timeout=args.timeout)
        except (ConnectionError, socket.timeout, OSError) as e:
            # Typed single-line error, not a traceback: an unreachable
            # process is a *finding* for an operator, not a crash.
            kind = ("timeout" if isinstance(e, socket.timeout)
                    else "connection_refused"
                    if isinstance(e, ConnectionRefusedError)
                    else "unreachable")
            print(f"scrape error: {kind}: {args.endpoint} "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(stats, default=str))
        else:
            print(json.dumps(stats, default=str, indent=2))
        return 0
    if args.trace:
        import os
        import sys

        from distkeras_tpu.telemetry.tracing import (render_trace_report,
                                                     trace_report)

        if not os.path.exists(args.path):
            # Contract (pinned by tests): a path that does not exist is an
            # operator error -> one line on stderr, exit 2. An EXISTING
            # dir with no records renders the empty report, exit 0 (a
            # fleet that traced nothing is a valid, boring answer).
            print(f"trace report: no such file or directory: {args.path}",
                  file=sys.stderr)
            return 2
        report = trace_report(merged_records(args.path))
        if args.json:
            print(json.dumps(report, default=float))
        else:
            print(render_trace_report(report), end="")
        return 0
    report = build_report(args.path, k=args.straggler_k)
    if args.json:
        print(json.dumps(report, default=float))
    else:
        print(render_report(report), end="")
    return 0
