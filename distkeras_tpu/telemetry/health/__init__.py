"""Fleet health plane: live SLO monitoring, burn-rate alerting, sentinels.

The observability layer ISSUE 15 adds on top of the per-process telemetry
core (PR 1) and the forensic tracing/flight plane (PR 14): something that
watches a *running* fleet and decides — before a chaos smoke would have
caught it post-hoc — that a tenant's serving p99 is burning its SLO, that
a shard's journal writer is falling behind, or that a registered process
has gone silent. Four pieces:

* :class:`~distkeras_tpu.telemetry.health.hub.MetricsHub` — a lightweight
  aggregation loop scraping every registered process over the
  membership-free ``stats`` op, keeping bounded in-memory time-series
  rings per metric (gauges + counter-derived rates + span histograms)
  with per-target NTP-style clock-offset estimates;
* :class:`~distkeras_tpu.telemetry.health.slo.SloEngine` — declarative
  SLO specs (JSON file or inline via ``DKTPU_HEALTH_SLO``) evaluated
  with multi-window burn-rate rules (fast + slow window), emitting typed
  ``health_alert`` / ``health_clear`` telemetry events and triggering a
  flight-recorder dump on page-severity alerts;
* :mod:`~distkeras_tpu.telemetry.health.sentinels` — anomaly detectors
  computed from the hub's rings (straggler drift, staleness creep,
  queue-depth growth, journal lag, shed spikes, silent targets, bench
  regression against BENCH_PIN/BENCH_SUMMARY bands);
* the CLIs — ``python -m distkeras_tpu.telemetry health`` (one-shot
  fleet summary) and ``... telemetry top`` (live refreshing view).

Everything stays stdlib-only and importable wherever the telemetry core
is. See docs/OBSERVABILITY.md ("Health & SLOs").
"""

from __future__ import annotations

from distkeras_tpu.telemetry.health.hub import (
    MetricsHub,
    TargetState,
    env_targets,
    parse_targets,
    register_target,
    registered_targets,
    unregister_target,
)
from distkeras_tpu.telemetry.health.sentinels import Sentinels
from distkeras_tpu.telemetry.health.slo import (
    AlertManager,
    SloEngine,
    SloSpec,
    parse_slo_specs,
)

__all__ = [
    "MetricsHub", "TargetState",
    "register_target", "unregister_target", "registered_targets",
    "parse_targets", "env_targets",
    "AlertManager", "SloEngine", "SloSpec", "parse_slo_specs",
    "Sentinels",
]
