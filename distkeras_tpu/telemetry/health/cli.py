"""The operator CLIs over the health plane.

``python -m distkeras_tpu.telemetry health`` — one-shot fleet summary:
scrape every target a couple of times (rates and burn windows need two
points), evaluate the SLO engine + sentinels, and render per-target
liveness/readiness, active alerts, and per-spec SLO attainment.
``--json`` emits the same structure for scripts.

``python -m distkeras_tpu.telemetry top`` — the same summary, live: a
refreshing terminal view driven by the hub's scrape loop until ^C.

Both take their targets from ``--targets``, the in-process registry,
and ``DKTPU_HEALTH_TARGETS``; SLO specs from ``--slo`` (inline JSON or
a file path) or ``DKTPU_HEALTH_SLO``.
"""

from __future__ import annotations

import io
import json
import time
from typing import Optional

from distkeras_tpu.telemetry.health.hub import MetricsHub, parse_targets
from distkeras_tpu.telemetry.health.sentinels import Sentinels
from distkeras_tpu.telemetry.health.slo import (
    AlertManager,
    SloEngine,
    parse_slo_specs,
)


def build_health_plane(targets: Optional[str] = None,
                       slo: Optional[str] = None,
                       interval: Optional[float] = None,
                       timeout: float = 1.0):
    """(hub, engine, sentinels) wired to one shared AlertManager."""
    hub = MetricsHub(targets=parse_targets(targets) if targets else None,
                     interval=interval, timeout=timeout)
    alerts = AlertManager()
    engine = SloEngine(parse_slo_specs(slo), alerts=alerts)
    sentinels = Sentinels(alerts=alerts)
    hub.on_sweep(engine.evaluate)
    hub.on_sweep(sentinels.evaluate)
    return hub, engine, sentinels


def health_snapshot(hub: MetricsHub, engine: SloEngine,
                    sentinels: Sentinels) -> dict:
    """The structured summary both CLIs render (and ``--json`` emits)."""
    sentinels.evaluate(hub)
    slos = engine.evaluate(hub)
    attainment = engine.attainment()
    alerts = engine.alerts.active()
    return {
        "sweeps": hub.sweeps,
        "targets": [
            {"name": t.name, "endpoint": t.endpoint, "role": t.role,
             "status": t.status(), "ready": t.ready,
             "misses": t.misses,
             "clock_offset_ms": (None if t.clock_offset_s is None
                                 else round(t.clock_offset_s * 1e3, 3)),
             "last_error": t.last_error}
            for t in sorted(hub.targets(), key=lambda t: t.name)],
        "alerts": [
            {"key": a.key, "severity": a.severity, "message": a.message,
             "value": a.value, **a.labels}
            for a in sorted(alerts.values(), key=lambda a: a.key)],
        "slos": {
            name: {**slos.get(name, {}),
                   "attainment": attainment.get(name)}
            for name in set(slos) | set(attainment)},
        "alerts_fired_total": engine.alerts.fired_total,
        "alerts_cleared_total": engine.alerts.cleared_total,
    }


def render_health(snap: dict) -> str:
    out = io.StringIO()
    w = out.write
    targets = snap["targets"]
    up = sum(1 for t in targets if t["status"] == "UP")
    w(f"== fleet health: {up}/{len(targets)} targets up, "
      f"{len(snap['alerts'])} active alert(s) "
      f"(fired {snap['alerts_fired_total']}, "
      f"cleared {snap['alerts_cleared_total']}) ==\n")
    if targets:
        w(f"{'target':<24} {'endpoint':<22} {'role':<10} {'status':<10} "
          f"{'ready':<6} {'clock ms':>9}\n")
        for t in targets:
            ready = ("-" if t["ready"] is None
                     else ("yes" if t["ready"] else "NO"))
            off = ("-" if t["clock_offset_ms"] is None
                   else f"{t['clock_offset_ms']:+.2f}")
            w(f"{t['name']:<24} {t['endpoint']:<22} "
              f"{(t['role'] or '-'):<10} {t['status']:<10} {ready:<6} "
              f"{off:>9}\n")
            if t["last_error"] and t["status"] != "UP":
                w(f"{'':<24}   {t['last_error']}\n")
    else:
        w("no targets (register some, pass --targets, or set "
          "DKTPU_HEALTH_TARGETS)\n")
    w("\n-- active alerts --\n")
    if snap["alerts"]:
        for a in snap["alerts"]:
            labels = {k: v for k, v in a.items()
                      if k not in ("key", "severity", "message", "value")}
            suffix = (" " + " ".join(f"{k}={v}"
                                     for k, v in sorted(labels.items()))
                      if labels else "")
            w(f"[{a['severity']:<6}] {a['key']}: {a['message']}{suffix}\n")
    else:
        w("none\n")
    if snap["slos"]:
        w("\n-- SLO attainment --\n")
        w(f"{'slo':<24} {'attain':>7} {'burn fast':>10} {'burn slow':>10}\n")
        for name in sorted(snap["slos"]):
            s = snap["slos"][name]
            att = s.get("attainment")
            bf, bs = s.get("burn_fast"), s.get("burn_slow")
            w(f"{name:<24} "
              f"{('-' if att is None else f'{att:.1%}'):>7} "
              f"{('-' if bf is None else f'{bf:.2f}'):>10} "
              f"{('-' if bs is None else f'{bs:.2f}'):>10}\n")
    return out.getvalue()


def cmd_health(args) -> int:
    hub, engine, sentinels = build_health_plane(
        targets=args.targets, slo=args.slo, timeout=args.timeout)
    # The engine/sentinels run on the on_sweep hook; burn windows and
    # rates need at least two points per target, hence samples >= 2.
    for i in range(max(1, args.samples)):
        if i:
            time.sleep(args.gap)
        hub.scrape_once()
    snap = health_snapshot(hub, engine, sentinels)
    if args.json:
        print(json.dumps(snap, default=str))
    else:
        print(render_health(snap), end="")
    return 0 if not snap["alerts"] else 1


def cmd_top(args) -> int:
    hub, engine, sentinels = build_health_plane(
        targets=args.targets, slo=args.slo, interval=args.interval,
        timeout=args.timeout)
    hub.start()
    try:
        n = 0
        while args.iterations <= 0 or n < args.iterations:
            n += 1
            time.sleep(hub.interval)
            snap = health_snapshot(hub, engine, sentinels)
            body = render_health(snap)
            if args.no_clear:
                print(body, end="", flush=True)
            else:
                print("\x1b[2J\x1b[H" + body, end="", flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        hub.close()
    return 0


def add_subcommands(sub) -> None:
    """Install ``health`` and ``top`` on the telemetry CLI's subparsers."""

    def common(p):
        p.add_argument("--targets", default=None,
                       help="scrape targets: `[name=]host:port` entries, "
                            "`;`-separated (default: the in-process "
                            "registry + DKTPU_HEALTH_TARGETS)")
        p.add_argument("--slo", default=None,
                       help="SLO specs: inline JSON or a file path "
                            "(default: DKTPU_HEALTH_SLO)")
        p.add_argument("--timeout", type=float, default=1.0,
                       help="per-target scrape timeout (default 1.0s)")

    h = sub.add_parser(
        "health", help="one-shot fleet health summary (per-target "
                       "liveness/readiness, active alerts, SLO "
                       "attainment); exit 1 when alerts are active")
    common(h)
    h.add_argument("--samples", type=int, default=2,
                   help="scrape sweeps before reporting (rates need two; "
                        "default 2)")
    h.add_argument("--gap", type=float, default=0.5,
                   help="seconds between sweeps (default 0.5)")
    h.add_argument("--json", action="store_true",
                   help="emit the structured summary as JSON")
    t = sub.add_parser(
        "top", help="live refreshing fleet health view (^C to exit)")
    common(t)
    t.add_argument("--interval", type=float, default=None,
                   help="refresh/scrape interval "
                        "(default DKTPU_HEALTH_INTERVAL)")
    t.add_argument("--iterations", type=int, default=0,
                   help="refresh this many times then exit (0 = forever; "
                        "tests)")
    t.add_argument("--no-clear", action="store_true",
                   help="append frames instead of clearing the screen "
                        "(non-ANSI terminals, logs)")
