"""Anomaly sentinels: detectors computed from the hub's rings.

Where SLOs encode objectives someone declared, sentinels encode shapes
that are *always* wrong: a registered process going silent, staleness
creeping up round over round, a queue that only grows, a journal writer
falling behind its commit stream, sheds appearing out of nowhere, and a
live throughput gauge sliding out of its BENCH_PIN band. Each sentinel
routes through the shared :class:`~.slo.AlertManager`, so fire/clear
hysteresis, typed events, and page→flight-dump behavior are identical
to SLO alerts.

Drift detectors compare the **fast** window against the trailing **slow**
window of the same metric (recent-vs-established ratio above a floor),
so they self-calibrate to whatever the workload's normal is instead of
needing absolute thresholds per deployment.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from distkeras_tpu.telemetry.health.slo import AlertManager


class Sentinels:
    """The standard detector set. All thresholds are instance attributes
    so tests (and operators embedding the hub) can tune them; the
    defaults are deliberately conservative — a sentinel that cries wolf
    is worse than none (the fault-free chaos leg pins zero alerts).
    """

    #: recent/established ratio a drift detector must exceed to fire.
    drift_factor: float = 2.0
    fast_s: float = 30.0
    slow_s: float = 300.0
    #: absolute floors under which drift is ignored (idle-fleet noise).
    staleness_floor: float = 1.0
    queue_floor: float = 16.0
    round_floor_s: float = 0.05
    journal_floor_s: float = 0.02
    shed_rate_floor: float = 0.5  # sheds/s in the fast window
    #: streaming eval loss under this is converged noise, not drift.
    stream_loss_floor: float = 0.05

    def __init__(self, alerts: Optional[AlertManager] = None,
                 bench_summary: Optional[str] = None,
                 bench_pin: Optional[str] = None) -> None:
        self.alerts = alerts or AlertManager()
        self.bench_summary = bench_summary
        self.bench_pin = bench_pin
        self._bench_keys: set = set()

    # -- evaluation ---------------------------------------------------------

    def evaluate(self, hub) -> None:
        self._target_down(hub)
        self._drift(hub, "staleness_creep", "*staleness_mean*", "mean",
                    self.staleness_floor)
        self._drift(hub, "queue_growth", "serving.queue_depth", "mean",
                    self.queue_floor)
        self._drift(hub, "queue_growth_ps", "stats.queue_rows", "mean",
                    self.queue_floor)
        self._drift(hub, "straggler_drift", "fleet.round.*", "span_mean",
                    self.round_floor_s)
        self._drift(hub, "journal_lag", "netps.journal.*", "span_mean",
                    self.journal_floor_s)
        # Fleet-level mirror of the in-runtime DriftWatch: the streaming
        # trainer's fast-window eval loss climbing against its own trailing
        # history is drift visible from the health plane alone.
        self._drift(hub, "stream_loss_divergence", "stream.eval.loss_fast",
                    "mean", self.stream_loss_floor)
        self._shed_spike(hub)
        self._bench_regression(hub)

    def _target_down(self, hub) -> None:
        down = {t.name for t in hub.down_targets()}
        seen = {t.name for t in hub.targets() if t.ever_up}
        for name in sorted(seen):
            t = hub.target(name)
            self.alerts.update(
                f"target_down:{name}", name in down, severity="page",
                message=(f"{name} ({t.endpoint if t else '?'}) stopped "
                         f"answering scrapes"),
                labels={"target": name})

    def _drift(self, hub, kind: str, metric: str, stat: str,
               floor: float) -> None:
        fast = hub.measure(metric, stat=stat, window_s=self.fast_s)
        slow = hub.measure(metric, stat=stat, window_s=self.slow_s)
        breaching = bool(
            fast is not None and slow is not None and fast > floor
            and slow > 0 and fast / slow > self.drift_factor)
        self.alerts.update(
            kind, breaching, severity="ticket",
            message=(f"{metric} {stat} drifted: fast={fast} vs "
                     f"slow={slow} (> {self.drift_factor}x)"),
            value=fast)

    def _shed_spike(self, hub) -> None:
        fast = hub.measure("serving.shed", stat="rate", window_s=self.fast_s)
        slow = hub.measure("serving.shed", stat="rate", window_s=self.slow_s)
        breaching = bool(
            fast is not None and fast > self.shed_rate_floor
            and (slow is None or fast > self.drift_factor * max(slow, 1e-9)))
        self.alerts.update(
            "shed_spike", breaching, severity="ticket",
            message=f"serving.shed rate spiked to {fast}/s", value=fast)

    # -- bench regression ---------------------------------------------------

    def _bench_regression(self, hub) -> None:
        """Two sources, same alert family: (1) a BENCH_SUMMARY.json whose
        per-config ``within_band`` already went false (the bench harness
        computed the comparison against BENCH_PIN); (2) live throughput
        gauges compared against the pins directly, for fleets running
        while a bench summary is stale or absent."""
        fresh = set()
        for reg in self.bench_regressions(self.bench_summary):
            key = f"bench_regression:{reg['metric']}"
            fresh.add(key)
            self.alerts.update(
                key, True, severity="ticket",
                message=(f"bench {reg['metric']}={reg['value']} outside "
                         f"pinned band (pin {reg.get('pin')})"),
                value=reg.get("value"))
        for key in self._bench_keys - fresh:  # summary repaired → clear
            self.alerts.update(key, False)
        self._bench_keys = fresh
        pins = self._load_pins()
        if not pins:
            return
        band = pins.get("weather_band_pct", 15) / 100.0
        for metric, cfg in (pins.get("configs") or {}).items():
            pin = cfg.get("pin")
            if not isinstance(pin, (int, float)) or pin <= 0:
                continue
            live = hub.measure(f"bench.{metric}", stat="value",
                               window_s=self.fast_s)
            breaching = bool(live is not None
                             and live < pin * (1.0 - band))
            self.alerts.update(
                f"bench_regression:live:{metric}", breaching,
                severity="ticket",
                message=(f"live {metric}={live} below pin {pin} "
                         f"band -{band:.0%}"),
                value=live)

    @staticmethod
    def bench_regressions(path: Optional[str] = None) -> List[Dict]:
        """Out-of-band configs from a BENCH_SUMMARY.json (doctored or
        real): every config whose ``within_band`` is explicitly false —
        including a config's nested ``sim_drift`` block (the simulator's
        predicted-vs-measured calibration gate, same alert family)."""
        path = path or "BENCH_SUMMARY.json"
        if not os.path.exists(path):
            return []
        try:
            with open(path, "r", encoding="utf-8") as f:
                summary = json.load(f)
        except (OSError, ValueError):
            return []
        out = []
        rows = list(summary.get("configs") or [])
        if "metric" in summary:
            rows.append(summary)
        rows.extend([cfg["sim_drift"] for cfg in list(rows)
                     if isinstance(cfg.get("sim_drift"), dict)])
        for cfg in rows:
            if cfg.get("within_band") is False:
                out.append({"metric": cfg.get("metric"),
                            "value": cfg.get("value"),
                            "pin": cfg.get("pin"),
                            "vs_baseline": cfg.get("vs_baseline")})
        return out

    def _load_pins(self) -> Optional[dict]:
        pin_path = self.bench_pin or "BENCH_PIN.json"
        if not os.path.exists(pin_path):
            return None
        try:
            with open(pin_path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None
