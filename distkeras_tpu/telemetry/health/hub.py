"""MetricsHub: the fleet-wide aggregation loop behind the health plane.

One lightweight thread periodically scrapes every registered process —
PS shards, standbys, serving replicas, fleet workers — over the same
membership-free ``stats`` op the ``telemetry scrape`` CLI uses: a raw
socket, no join, no lease, works against a fenced ex-primary or a
mid-warmup replica. Each sweep folds the reply into bounded in-memory
time-series rings:

* telemetry **gauges** → ``(ts, value)`` points;
* telemetry **counters** → derived **rates** (delta / dt between
  consecutive scrapes, reset-safe across process restarts);
* telemetry **spans** → cumulative histogram snapshots, so a windowed
  p99 is the bucket-quantile of the *difference* between the window's
  edges — quantiles over exactly the window, not since-boot;
* scalar reply fields (``commits_total``, ``queue_rows``, ``members``,
  ...) → ``stats.<field>`` gauges (and rates for the cumulative ones).

Scrapes piggyback the PR 14 clock exchange: every request stamps
``ct0`` and the server echoes ``st1``/``st2``, so the hub keeps a
min-RTT NTP-style offset estimate *per target* (the tracing-collector
math, but one estimator per process instead of the module-global one a
worker keeps toward its PS). Ring timestamps stay on the hub's clock —
the one timeline every target shares — and the per-target offsets are
surfaced for drift display and for aligning any server-side timestamps.

A registered target that stops answering flips to ``down`` after
``DKTPU_HEALTH_DOWN_AFTER`` consecutive misses; the sentinel layer turns
that into a typed ``target_down`` alert and ``Job.supervise`` /
``FleetScheduler`` can consult :meth:`MetricsHub.is_down` to restart on
failed liveness instead of waiting for a lease to lapse.

Fleet components self-register via :func:`register_target`; ad-hoc
processes are added with ``DKTPU_HEALTH_TARGETS`` (``[name=]host:port``
entries, ``;``- or ``,``-separated). Both are re-read every sweep, so a
replica that comes up after the hub starts is scraped on the next tick.
"""

from __future__ import annotations

import fnmatch
import socket
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from distkeras_tpu.runtime.config import env_float, env_int, env_str

#: Reply fields (outside the telemetry snapshot) that grow monotonically —
#: the hub derives a rate ring for these on top of the ``stats.<k>`` gauge.
_CUMULATIVE_FIELDS = ("commits_total", "served", "updates", "compiles")

#: Scalar reply fields mirrored into ``stats.<k>`` gauges each sweep.
_SCALAR_FIELDS = _CUMULATIVE_FIELDS + (
    "epoch", "members", "queue_rows", "version", "draining")


def parse_targets(spec: str) -> Dict[str, str]:
    """``[name=]host:port`` entries (``;`` or ``,`` separated) → ``{name:
    endpoint}``. A bare endpoint names itself."""
    out: Dict[str, str] = {}
    for part in spec.replace(",", ";").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, endpoint = part.split("=", 1)
            out[name.strip()] = endpoint.strip()
        else:
            out[part] = part
    return out


def env_targets() -> Dict[str, str]:
    """Ad-hoc targets from ``DKTPU_HEALTH_TARGETS``."""
    return parse_targets(env_str("DKTPU_HEALTH_TARGETS"))


_registry_lock = threading.Lock()
_registry: Dict[str, str] = {}


def register_target(endpoint: str, name: Optional[str] = None) -> str:
    """Register a scrape target with the in-process hub registry (fleet
    components call this when they bind an endpoint). Returns the name
    under which the target was filed. Idempotent; a re-register with the
    same name just updates the endpoint (restarts move ports)."""
    name = name or endpoint
    with _registry_lock:
        _registry[name] = endpoint
    return name


def unregister_target(name_or_endpoint: str) -> None:
    with _registry_lock:
        if name_or_endpoint in _registry:
            del _registry[name_or_endpoint]
            return
        for k, v in list(_registry.items()):
            if v == name_or_endpoint:
                del _registry[k]


def registered_targets() -> Dict[str, str]:
    with _registry_lock:
        return dict(_registry)


class _OffsetEstimator:
    """Per-target min-RTT clock offset (the tracing ``clock`` math, local
    to one target instead of module-global)."""

    def __init__(self) -> None:
        self.offset = 0.0
        self.rtt = float("inf")
        self.samples = 0

    def observe(self, ct0: float, st1: float, st2: float,
                ct3: float) -> None:
        rtt = (ct3 - ct0) - (st2 - st1)
        self.samples += 1
        if rtt < self.rtt:
            self.rtt = rtt
            self.offset = ((st1 - ct0) + (st2 - ct3)) / 2.0


@dataclass
class TargetState:
    """Everything the hub knows about one scrape target. Rings are
    bounded deques of hub-clock points; ``spans`` entries are cumulative
    ``(ts, count, total, buckets)`` snapshots (window math diffs them)."""

    name: str
    endpoint: str
    role: Optional[str] = None
    ready: Optional[bool] = None
    caps: Optional[dict] = None
    misses: int = 0
    down: bool = False
    ever_up: bool = False
    last_ok: Optional[float] = None
    last_error: Optional[str] = None
    clock_offset_s: Optional[float] = None
    clock_rtt_s: Optional[float] = None
    gauges: Dict[str, deque] = field(default_factory=dict)
    rates: Dict[str, deque] = field(default_factory=dict)
    spans: Dict[str, deque] = field(default_factory=dict)
    _last_counters: Dict[str, Tuple[float, float]] = field(
        default_factory=dict)
    _clock: _OffsetEstimator = field(default_factory=_OffsetEstimator)

    def status(self) -> str:
        if self.down:
            return "DOWN"
        if not self.ever_up:
            return "PENDING"
        if self.ready is False:
            return "NOT-READY"
        return "UP"


class MetricsHub:
    """Bounded time-series store + scrape loop over the fleet's stats op.

    ``interval``/``ring``/``down_after`` default from the
    ``DKTPU_HEALTH_INTERVAL``/``DKTPU_HEALTH_RING``/
    ``DKTPU_HEALTH_DOWN_AFTER`` EnvVars; explicit ctor targets are merged
    with the in-process registry and ``DKTPU_HEALTH_TARGETS`` on every
    sweep.
    """

    def __init__(self, targets: Optional[Dict[str, str]] = None,
                 interval: Optional[float] = None,
                 ring: Optional[int] = None,
                 down_after: Optional[int] = None,
                 timeout: float = 1.0,
                 use_registry: bool = True,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self._static = dict(targets or {})
        #: the hub's timeline. Injectable so the simulator
        #: (``distkeras_tpu.sim``) can run the real windowed-measure /
        #: burn-rate math on a virtual clock; None = wall clock.
        self._clock: Callable[[], float] = clock or time.time
        self.interval = (env_float("DKTPU_HEALTH_INTERVAL")
                         if interval is None else float(interval))
        self.ring = max(2, env_int("DKTPU_HEALTH_RING")
                        if ring is None else int(ring))
        self.down_after = max(1, env_int("DKTPU_HEALTH_DOWN_AFTER")
                              if down_after is None else int(down_after))
        self.timeout = float(timeout)
        self.use_registry = use_registry
        self._lock = threading.Lock()
        self._targets: Dict[str, TargetState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._on_sweep: List[Callable[["MetricsHub"], None]] = []
        #: cumulative histogram state behind :meth:`feed`'s span kind,
        #: keyed (target, metric).
        self._fed_spans: Dict[Tuple[str, str], list] = {}
        self.sweeps = 0

    # -- target management -------------------------------------------------

    def _known_targets(self) -> Dict[str, str]:
        merged = dict(self._static)
        if self.use_registry:
            merged.update(registered_targets())
            merged.update(env_targets())
        return merged

    def add_target(self, endpoint: str, name: Optional[str] = None) -> str:
        name = name or endpoint
        self._static[name] = endpoint
        return name

    def remove_target(self, name: str) -> None:
        self._static.pop(name, None)
        with self._lock:
            self._targets.pop(name, None)

    def targets(self) -> List[TargetState]:
        with self._lock:
            return list(self._targets.values())

    def target(self, name: str) -> Optional[TargetState]:
        with self._lock:
            return self._targets.get(name)

    def is_down(self, name_or_endpoint: str) -> bool:
        """Liveness answer for supervisors: True only for a target that
        was scraped successfully at least once and has now missed
        ``down_after`` consecutive sweeps (a target we never reached is
        PENDING, not down — don't shoot a process that is still
        binding its socket)."""
        with self._lock:
            for t in self._targets.values():
                if name_or_endpoint in (t.name, t.endpoint):
                    return t.down and t.ever_up
        return False

    def down_targets(self) -> List[TargetState]:
        return [t for t in self.targets() if t.down and t.ever_up]

    def on_sweep(self, fn: Callable[["MetricsHub"], None]) -> None:
        """Run ``fn(hub)`` after every sweep (SLO engine / sentinels hook
        in here so evaluation happens on fresh data, on the hub thread)."""
        self._on_sweep.append(fn)

    # -- scraping ----------------------------------------------------------

    def _scrape(self, endpoint: str) -> Tuple[dict, float, float]:
        from distkeras_tpu.netps import wire

        host, port = wire.split_endpoint(endpoint)
        ct0 = time.time()
        with socket.create_connection((host, port),
                                      timeout=self.timeout) as sock:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(self.timeout)
            wire.send_frame(sock, wire.KIND_REQUEST,
                            {"op": wire.OP_STATS, "req": 0, "ring": 0,
                             "ct0": ct0}, [])
            while True:
                kind, rhdr, _arrays = wire.read_frame(sock)
                if kind == wire.KIND_REPLY and rhdr.get("req") == 0:
                    return rhdr, ct0, time.time()

    def scrape_once(self) -> int:
        """One sweep over every known target. Returns how many answered."""
        known = self._known_targets()
        ok = 0
        for name, endpoint in known.items():
            with self._lock:
                t = self._targets.get(name)
                if t is None or t.endpoint != endpoint:
                    t = TargetState(name=name, endpoint=endpoint)
                    self._targets[name] = t
            try:
                reply, ct0, ct3 = self._scrape(endpoint)
            except (OSError, socket.timeout) as exc:
                with self._lock:
                    t.misses += 1
                    t.last_error = f"{type(exc).__name__}: {exc}"
                    if t.misses >= self.down_after:
                        t.down = True
                continue
            with self._lock:
                self._ingest(t, reply, ct0, ct3)
            ok += 1
        # Drop state for targets no longer known anywhere (unregistered).
        with self._lock:
            for name in list(self._targets):
                if name not in known:
                    del self._targets[name]
        self.sweeps += 1
        for fn in list(self._on_sweep):
            fn(self)
        return ok

    def _ring(self, store: Dict[str, deque], name: str) -> deque:
        ring = store.get(name)
        if ring is None:
            ring = store[name] = deque(maxlen=self.ring)
        return ring

    def _ingest(self, t: TargetState, reply: dict, ct0: float,
                ct3: float) -> None:
        now = (ct0 + ct3) / 2.0  # hub clock; midpoint kills send/recv skew
        t.misses = 0
        t.down = False
        t.ever_up = True
        t.last_ok = now
        t.last_error = None
        t.role = reply.get("role", t.role)
        if "ready" in reply:
            t.ready = bool(reply["ready"])
        if reply.get("caps") is not None:
            t.caps = reply.get("caps")
        st1, st2 = reply.get("st1"), reply.get("st2")
        if st1 is not None and st2 is not None:
            t._clock.observe(ct0, st1, st2, ct3)
            t.clock_offset_s = t._clock.offset
            t.clock_rtt_s = t._clock.rtt
        for k in _SCALAR_FIELDS:
            v = reply.get(k)
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, (int, float)):
                self._ring(t.gauges, f"stats.{k}").append((now, float(v)))
                if k in _CUMULATIVE_FIELDS:
                    self._rate_point(t, f"stats.{k}", now, float(v))
        snapshot = reply.get("snapshot") or {}
        for name, v in (snapshot.get("counters") or {}).items():
            self._rate_point(t, name, now, float(v))
        for name, g in (snapshot.get("gauges") or {}).items():
            value = g.get("value") if isinstance(g, dict) else g
            if isinstance(value, (int, float)):
                self._ring(t.gauges, name).append((now, float(value)))
        for name, h in (snapshot.get("spans") or {}).items():
            if not isinstance(h, dict):
                continue
            self._ring(t.spans, name).append(
                (now, int(h.get("count", 0)), float(h.get("total", 0.0)),
                 tuple(h.get("buckets", ()))))

    # -- the metric-feed seam ----------------------------------------------

    def _feed_target(self, name: str, role: Optional[str]) -> TargetState:
        """Lock held. Fed targets join ``_static`` so a stray
        ``scrape_once`` does not garbage-collect their rings."""
        t = self._targets.get(name)
        if t is None:
            t = TargetState(name=name, endpoint=name)
            self._targets[name] = t
            self._static.setdefault(name, name)
        if role is not None:
            t.role = role
        return t

    def feed(self, target: str, metric: str, value: float, *,
             kind: str = "gauge", ts: Optional[float] = None,
             role: Optional[str] = None) -> None:
        """Inject one synthesized observation as if a scrape returned it
        — the seam the fleet simulator (and any replay tool) uses to run
        the REAL ring/window/burn-rate/hysteresis machinery against
        series that never crossed a socket.

        ``kind``: ``"gauge"`` appends a point; ``"counter"`` takes the
        cumulative total and derives the same reset-safe rate a scrape
        would; ``"span"`` takes one duration sample and accumulates it
        into a cumulative histogram snapshot (so windowed p99s diff
        exactly like scraped ones). A fed point also counts as liveness:
        misses reset, ``ever_up`` latches — pair with :meth:`feed_miss`
        to simulate a target going dark."""
        ts = self._clock() if ts is None else float(ts)
        with self._lock:
            t = self._feed_target(target, role)
            t.misses = 0
            t.down = False
            t.ever_up = True
            t.last_ok = ts
            t.last_error = None
            if kind == "gauge":
                self._ring(t.gauges, metric).append((ts, float(value)))
            elif kind == "counter":
                self._rate_point(t, metric, ts, float(value))
            elif kind == "span":
                self._feed_span(t, metric, ts, float(value))
            else:
                raise ValueError(
                    f"feed kind must be gauge/counter/span, got {kind!r}")

    def feed_miss(self, target: str, role: Optional[str] = None) -> None:
        """The feed-side mirror of a failed scrape: one more consecutive
        miss; ``down`` flips after ``down_after`` of them (real
        :meth:`is_down` semantics — a never-up target stays PENDING)."""
        with self._lock:
            t = self._feed_target(target, role)
            t.misses += 1
            t.last_error = "fed miss"
            if t.misses >= self.down_after:
                t.down = True

    def _feed_span(self, t: TargetState, metric: str, ts: float,
                   dur_s: float) -> None:
        """Accumulate one duration sample into the target's cumulative
        histogram for ``metric`` (same bucket walk as
        ``telemetry.core``) and snapshot it into the span ring."""
        import bisect

        from distkeras_tpu.telemetry.core import BUCKET_BOUNDS

        key = (t.name, metric)
        count, total, buckets = self._fed_spans.setdefault(
            key, [0, 0.0, [0] * (len(BUCKET_BOUNDS) + 1)])
        count += 1
        total += dur_s
        buckets[bisect.bisect_left(BUCKET_BOUNDS, dur_s)] += 1
        self._fed_spans[key] = [count, total, buckets]
        self._ring(t.spans, metric).append(
            (ts, count, total, tuple(buckets)))

    def _rate_point(self, t: TargetState, name: str, now: float,
                    cum: float) -> None:
        last = t._last_counters.get(name)
        t._last_counters[name] = (now, cum)
        if last is None:
            return
        ts0, c0 = last
        dt = now - ts0
        if dt <= 0:
            return
        if cum < c0:  # process restarted: counter reset — re-base, no point
            return
        self._ring(t.rates, name).append((now, (cum - c0) / dt))

    # -- the loop ----------------------------------------------------------

    def start(self) -> "MetricsHub":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dktpu-health-hub", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # a sweep must never kill the hub
                pass
            self._stop.wait(self.interval)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "MetricsHub":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- windowed measurement ----------------------------------------------

    def _matching(self, target_glob: Optional[str]) -> List[TargetState]:
        """Lock held by the caller (``measure``) — reads ``_targets``
        directly; ``self._lock`` is not reentrant."""
        out = []
        for t in self._targets.values():
            if target_glob is None or fnmatch.fnmatch(
                    t.name, target_glob) or (
                    t.role and fnmatch.fnmatch(t.role, target_glob)):
                out.append(t)
        return out

    @staticmethod
    def _window_points(ring: deque, lo: float) -> List[float]:
        return [v for ts, v in ring if ts >= lo]

    @staticmethod
    def _span_window(ring: deque, lo: float):
        """Cumulative-histogram diff across the window: (count, total,
        buckets) accrued since the last snapshot at-or-before ``lo``."""
        base = None
        head = None
        for entry in ring:
            if entry[0] < lo:
                base = entry
            else:
                head = entry
        if head is None:
            return None
        _, c1, tot1, b1 = head
        if base is None:
            return c1, tot1, list(b1)
        _, c0, tot0, b0 = base
        buckets = [max(0, a - b) for a, b in
                   zip(b1, list(b0) + [0] * (len(b1) - len(b0)))]
        return max(0, c1 - c0), max(0.0, tot1 - tot0), buckets

    def measure(self, metric: str, stat: str = "value",
                window_s: float = 60.0,
                target: Optional[str] = None) -> Optional[float]:
        """One number for ``metric`` over the trailing window, aggregated
        across matching targets. ``metric`` may be a glob (label-suffixed
        families like ``fleet.examples_per_sec.tenantA.*`` aggregate).

        stats: ``value`` (latest gauge), ``mean`` (gauge mean), ``max``,
        ``rate`` (summed counter rates), ``p50``/``p90``/``p99`` (bucket
        quantile of the windowed span diff, merged across targets),
        ``span_mean`` (windowed mean span duration). None when no data
        landed in the window — absence of evidence is not a breach.
        """
        lo = self._clock() - window_s
        if stat == "rate":
            per_target = []
            with self._lock:
                for t in self._matching(target):
                    vals: List[float] = []
                    for name, ring in t.rates.items():
                        if fnmatch.fnmatch(name, metric):
                            vals.extend(self._window_points(ring, lo))
                    if vals:
                        per_target.append(sum(vals) / len(vals))
            return sum(per_target) if per_target else None
        if stat in ("value", "mean", "max"):
            vals = []
            with self._lock:
                for t in self._matching(target):
                    for name, ring in t.gauges.items():
                        if not fnmatch.fnmatch(name, metric):
                            continue
                        pts = self._window_points(ring, lo)
                        if not pts:
                            continue
                        if stat == "value":
                            vals.append(pts[-1])
                        elif stat == "max":
                            vals.append(max(pts))
                        else:
                            vals.append(sum(pts) / len(pts))
            if not vals:
                return None
            return max(vals) if stat == "max" else sum(vals) / len(vals)
        # span stats: merge windowed histogram diffs across targets
        count = 0
        total = 0.0
        buckets: List[int] = []
        with self._lock:
            for t in self._matching(target):
                for name, ring in t.spans.items():
                    if not fnmatch.fnmatch(name, metric):
                        continue
                    diff = self._span_window(ring, lo)
                    if diff is None:
                        continue
                    c, tot, b = diff
                    count += c
                    total += tot
                    if len(b) > len(buckets):
                        buckets.extend([0] * (len(b) - len(buckets)))
                    for i, x in enumerate(b):
                        buckets[i] += x
        if not count:
            return None
        if stat == "span_mean":
            return total / count
        if stat.startswith("p"):
            q = float(stat[1:]) / (100.0 if len(stat) <= 3 else 1000.0)
            return _bucket_quantile(buckets, count, q)
        return None

    def metric_names(self) -> Dict[str, List[str]]:
        """Every metric the hub has seen, by kind (CLI discovery aid)."""
        g, r, s = set(), set(), set()
        with self._lock:
            for t in self._targets.values():
                g.update(t.gauges)
                r.update(t.rates)
                s.update(t.spans)
        return {"gauges": sorted(g), "rates": sorted(r), "spans": sorted(s)}


def _bucket_quantile(buckets: List[int], count: int, q: float) -> float:
    """Same walk as ``report._hist_quantile`` over a windowed diff."""
    from distkeras_tpu.telemetry.core import BUCKET_BOUNDS

    def bound(i: int) -> float:
        return BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) else BUCKET_BOUNDS[-1]

    target = q * count
    seen = 0
    top = 0.0
    for i, c in enumerate(buckets):
        if c:
            top = bound(i)
        seen += c
        if seen >= target and c:
            return bound(i)
    return top
