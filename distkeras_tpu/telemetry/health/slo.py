"""Declarative SLOs, multi-window burn rates, and the alert manager.

An SLO spec is a JSON object (a file of them, or inline JSON in
``DKTPU_HEALTH_SLO``) naming a hub metric, a stat, and a bound::

    {"name": "serve-p99", "metric": "serving.latency", "stat": "p99",
     "max": 0.25, "fast_s": 30, "slow_s": 300, "severity": "page",
     "labels": {"tenant": "B"}}

``max`` caps the measurement (latency, shed rate, staleness, journal
lag); ``min`` floors it (per-tenant tokens/s). The **burn rate** is how
fast the objective is being consumed: ``measured / max`` for a cap,
``min / measured`` for a floor — 1.0 exactly at the objective. An alert
fires only when the burn exceeds 1 in **both** the fast and the slow
window (the multi-window rule: the fast window gives low detection
latency, the slow window vetoes one-scrape blips), and clears with
hysteresis once both windows are back under.

:class:`AlertManager` owns fire/clear for SLOs *and* sentinels: typed
``health_alert`` / ``health_clear`` telemetry events with the spec's
tenant/job labels, ``health.alerts_fired`` / ``health.alerts_cleared``
counters, and — on page-severity fires — a flight-recorder dump
(``tracing.flight_dump``) so every page ships its own evidence.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from distkeras_tpu import telemetry
from distkeras_tpu.runtime.config import env_str

SEVERITIES = ("page", "ticket")


@dataclass
class SloSpec:
    """One declarative objective over a hub metric."""

    name: str
    metric: str
    stat: str = "value"
    max: Optional[float] = None
    min: Optional[float] = None
    fast_s: float = 30.0
    slow_s: float = 300.0
    severity: str = "ticket"
    target: Optional[str] = None  # glob over target name/role
    labels: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if (self.max is None) == (self.min is None):
            raise ValueError(
                f"SLO {self.name!r}: exactly one of max/min required")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"SLO {self.name!r}: severity must be one of {SEVERITIES}")
        if self.fast_s <= 0 or self.slow_s < self.fast_s:
            raise ValueError(
                f"SLO {self.name!r}: need 0 < fast_s <= slow_s")

    def burn(self, measured: Optional[float]) -> Optional[float]:
        """Burn rate: >1 means the objective is being violated. None when
        there is no measurement (no data is not a breach)."""
        if measured is None:
            return None
        if self.max is not None:
            if self.max <= 0:
                return float("inf") if measured > 0 else 0.0
            return measured / self.max
        assert self.min is not None
        if measured <= 0:
            return float("inf")
        return self.min / measured


def parse_slo_specs(text: Optional[str] = None) -> List[SloSpec]:
    """SLO specs from inline JSON, a file path, or ``DKTPU_HEALTH_SLO``
    (which may itself be inline JSON — starts with ``[`` or ``{`` — or a
    path). Accepts a single object or a list."""
    if text is None:
        text = env_str("DKTPU_HEALTH_SLO")
    text = (text or "").strip()
    if not text:
        return []
    if not text.startswith(("[", "{")):
        if not os.path.exists(text):
            raise ValueError(f"SLO spec file not found: {text}")
        with open(text, "r", encoding="utf-8") as f:
            text = f.read().strip()
    raw = json.loads(text)
    if isinstance(raw, dict):
        raw = [raw]
    specs = []
    for obj in raw:
        if not isinstance(obj, dict) or "name" not in obj or \
                "metric" not in obj:
            raise ValueError(f"SLO spec needs name+metric: {obj!r}")
        known = {"name", "metric", "stat", "max", "min", "fast_s",
                 "slow_s", "severity", "target", "labels"}
        unknown = set(obj) - known
        if unknown:
            raise ValueError(
                f"SLO {obj.get('name')!r}: unknown keys {sorted(unknown)}")
        specs.append(SloSpec(**obj))
    return specs


@dataclass
class Alert:
    key: str
    severity: str
    message: str
    labels: Dict[str, str]
    fired_at: float
    value: Optional[float] = None


class AlertManager:
    """Fire/clear bookkeeping shared by the SLO engine and the sentinels.

    ``clear_after`` consecutive healthy evaluations are required before a
    fired alert clears (hysteresis — a breach that flaps around the
    threshold holds the alert instead of spamming fire/clear pairs).
    Fires emit ``health_alert`` events; page severity also drops a
    flight-recorder dump named after the alert key.
    """

    def __init__(self, clear_after: int = 2) -> None:
        self.clear_after = max(1, int(clear_after))
        self._lock = threading.Lock()
        self._active: Dict[str, Alert] = {}
        self._calm: Dict[str, int] = {}
        self.fired_total = 0
        self.cleared_total = 0
        self.history: List[dict] = []

    def update(self, key: str, breaching: bool, severity: str = "ticket",
               message: str = "", labels: Optional[Dict[str, str]] = None,
               value: Optional[float] = None) -> Optional[str]:
        """Advance one condition. Returns ``"fired"`` / ``"cleared"`` on
        a transition, None otherwise."""
        labels = dict(labels or {})
        with self._lock:
            active = key in self._active
            if breaching:
                self._calm[key] = 0
                if active:
                    self._active[key].value = value
                    return None
                alert = Alert(key=key, severity=severity, message=message,
                              labels=labels, fired_at=time.time(),
                              value=value)
                self._active[key] = alert
                self.fired_total += 1
                self.history.append({"event": "fired", "key": key,
                                     "severity": severity,
                                     "message": message, "value": value,
                                     **labels})
            else:
                if not active:
                    return None
                calm = self._calm.get(key, 0) + 1
                self._calm[key] = calm
                if calm < self.clear_after:
                    return None
                alert = self._active.pop(key)
                del self._calm[key]
                self.cleared_total += 1
                self.history.append({"event": "cleared", "key": key,
                                     "severity": alert.severity,
                                     **alert.labels})
        # Emit outside the lock: the event tap is user code.
        if breaching:
            telemetry.counter("health.alerts_fired").add(1)
            telemetry.event("health_alert",
                            {"alert": key, "severity": severity,
                             "message": message, "value": value, **labels})
            if severity == "page":
                from distkeras_tpu.telemetry.tracing import flight_dump

                flight_dump(f"health:{key}", once=True)
            return "fired"
        telemetry.counter("health.alerts_cleared").add(1)
        telemetry.event("health_clear",
                        {"alert": key, "severity": alert.severity,
                         **alert.labels})
        return "cleared"

    def active(self) -> Dict[str, Alert]:
        with self._lock:
            return dict(self._active)

    def is_active(self, key: str) -> bool:
        with self._lock:
            return key in self._active


class SloEngine:
    """Evaluates every spec against the hub on demand (typically from the
    hub's ``on_sweep`` hook) and tracks per-spec attainment: the share of
    evaluations-with-data whose fast window met the objective."""

    def __init__(self, specs: List[SloSpec],
                 alerts: Optional[AlertManager] = None) -> None:
        self.specs = list(specs)
        self.alerts = alerts or AlertManager()
        self._evals: Dict[str, int] = {}
        self._ok: Dict[str, int] = {}

    def evaluate(self, hub) -> Dict[str, dict]:
        """One pass; returns per-spec ``{burn_fast, burn_slow, breaching,
        measured_fast}`` for the CLIs."""
        out: Dict[str, dict] = {}
        for spec in self.specs:
            fast = hub.measure(spec.metric, stat=spec.stat,
                               window_s=spec.fast_s, target=spec.target)
            slow = hub.measure(spec.metric, stat=spec.stat,
                               window_s=spec.slow_s, target=spec.target)
            burn_fast = spec.burn(fast)
            burn_slow = spec.burn(slow)
            breaching = bool(burn_fast is not None and burn_fast > 1.0
                             and burn_slow is not None and burn_slow > 1.0)
            if burn_fast is not None:
                self._evals[spec.name] = self._evals.get(spec.name, 0) + 1
                if burn_fast <= 1.0:
                    self._ok[spec.name] = self._ok.get(spec.name, 0) + 1
            bound = spec.max if spec.max is not None else spec.min
            word = "<=" if spec.max is not None else ">="
            self.alerts.update(
                f"slo:{spec.name}", breaching, severity=spec.severity,
                message=(f"{spec.metric} {spec.stat}={fast} violates "
                         f"{word} {bound} (burn fast={burn_fast}, "
                         f"slow={burn_slow})"),
                labels=spec.labels, value=fast)
            out[spec.name] = {"burn_fast": burn_fast,
                              "burn_slow": burn_slow,
                              "breaching": breaching,
                              "measured_fast": fast}
        return out

    def attainment(self) -> Dict[str, Optional[float]]:
        """Per-spec attainment in [0, 1]; None before any data."""
        out: Dict[str, Optional[float]] = {}
        for spec in self.specs:
            n = self._evals.get(spec.name, 0)
            out[spec.name] = (self._ok.get(spec.name, 0) / n) if n else None
        return out
