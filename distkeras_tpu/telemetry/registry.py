"""The metric-name registry: every telemetry name, declared once.

PR 3 pinned the ``DKTPU_*`` env surface to ``runtime/config.py``'s
``ENV_REGISTRY``; this module does the same for the telemetry surface.
Every ``counter``/``gauge``/``histogram``/``span`` name the package emits
is declared here with its kind and one-line doc — dk-check's DK601 fails
the build on a name literal this registry doesn't know, and DK602 fails
it when the generated docs tables drift (regenerate with ``python -m
distkeras_tpu.analysis --write-metric-docs``, the ``--write-env-docs``
pattern).

``dynamic=True`` rows are *prefixes*: the runtime appends a computed
suffix (the fleet plane's ``.tenant.job`` attribution, the sharded
center's ``.<k>`` shard index, the server span's op + transport dialect).
A static literal is declared iff it equals a static row's name or extends
a dynamic row's prefix; an f-string is declared iff its leading constant
is compatible with a dynamic row.

The registry is aggregation-free metadata — importing it never touches
the live :mod:`distkeras_tpu.telemetry` registry object.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional

#: metric kinds, matching the four name-taking telemetry accessors.
KINDS = ("counter", "gauge", "histogram", "span")


@dataclasses.dataclass(frozen=True)
class Metric:
    """One declared telemetry name (or name prefix when ``dynamic``)."""

    name: str
    kind: str
    category: str
    doc: str
    dynamic: bool = False


def _m(name: str, kind: str, category: str, doc: str,
       dynamic: bool = False) -> Metric:
    if kind not in KINDS:
        raise ValueError(f"unknown metric kind {kind!r} for {name!r}")
    return Metric(name, kind, category, doc, dynamic)


#: THE declaration list (grouped by category; order is the docs order).
_METRICS = [
    # -- training loop (MetricsLogger core) ------------------------------
    _m("rounds", "counter", "training",
       "Training rounds recorded by MetricsLogger."),
    _m("round_seconds", "histogram", "training",
       "Wall-clock seconds per recorded round."),
    _m("loss", "gauge", "training",
       "Most recent per-round loss (min/max/mean tracked)."),
    # -- engine run loops -------------------------------------------------
    _m("engine_run", "span", "engine",
       "Anchor span for one engine run loop; phase spans nest under it."),
    _m("dispatch[per-round]", "span", "engine",
       "Host enqueue latency, per-round blocking dispatch."),
    _m("dispatch[auto]", "span", "engine",
       "Host enqueue latency under auto-blocked (bursty) dispatch."),
    _m("dispatch[stream]", "span", "engine",
       "Host enqueue latency on the streaming dispatch path."),
    _m("retire[per-round]", "span", "engine",
       "Per-round retire fence: the blocking loss fetch."),
    _m("retire[stream]", "span", "engine",
       "Streaming retire fence: end-of-run drain."),
    _m("input_stall", "histogram", "engine",
       "Consumer time blocked on the data plane, per round."),
    _m("input_stall_seconds", "counter", "engine",
       "Total consumer seconds blocked on the data plane."),
    _m("pipeline.dispatch", "span", "engine",
       "Pipeline engine step dispatch latency."),
    _m("stage[tp-local]", "span", "engine",
       "AsyncTP local parameter staging per round."),
    # -- data plane -------------------------------------------------------
    _m("feeder.stage", "histogram", "data",
       "Producer-side gather+transform+device_put seconds per round."),
    _m("feeder.queue_depth", "gauge", "data",
       "Prefetch queue depth at each pop (0 = stalls imminent)."),
    _m("feeder.fill_ratio", "gauge", "data",
       "Prefetch fill ratio at each pop (1.0 = staging fully hidden)."),
    _m("native.gather", "span", "data",
       "Native loader gather latency."),
    _m("native.gather_calls", "counter", "data",
       "Native gather invocations."),
    _m("native.gather_bytes", "counter", "data",
       "Bytes moved by the native gather path."),
    _m("native.gather_fallback_calls", "counter", "data",
       "Silent numpy fallbacks (a data-plane regression signal)."),
    # -- inference --------------------------------------------------------
    _m("predict.chunk", "span", "inference",
       "Per-chunk end-to-end predict latency."),
    _m("predict.rows", "counter", "inference",
       "Rows predicted."),
    _m("predict.padded_rows", "counter", "inference",
       "Rows of batch padding added by the predictor."),
    _m("predict.pending_rows", "gauge", "inference",
       "Streaming-predict backlog in rows."),
    _m("predict.shard_rows", "histogram", "inference",
       "Rows per predict shard (skew = max/mean)."),
    _m("predict.shard_seconds", "histogram", "inference",
       "Seconds per predict shard."),
    _m("predict.stream_microbatch", "span", "inference",
       "Streaming-inference micro-batch (ingest+compute only)."),
    _m("predict.stream_rows", "counter", "inference",
       "Rows answered by streaming inference."),
    # -- disciplines ------------------------------------------------------
    _m("discipline.staleness_mean", "gauge", "disciplines",
       "Mean realized staleness charged by the discipline."),
    _m("discipline.staleness_max", "gauge", "disciplines",
       "Max realized staleness charged by the discipline."),
    _m("discipline.dynsgd_scale_min", "gauge", "disciplines",
       "Smallest DynSGD scale (1/(staleness+1)) applied."),
    _m("discipline.loss_divergence_max", "gauge", "disciplines",
       "Largest per-worker loss divergence from the mean."),
    _m("discipline.straggler_rounds", "counter", "disciplines",
       "Rounds flagged as stragglers (time > k x running median)."),
    # -- resilience -------------------------------------------------------
    _m("resilience.nonfinite_rounds", "counter", "resilience",
       "Rounds the NaN/Inf guard skipped."),
    _m("resilience.feeder_stall_warnings", "counter", "resilience",
       "Feeder stall watchdog warnings."),
    _m("resilience.feeder_stall_deaths", "counter", "resilience",
       "Feeders declared dead by the stall watchdog."),
    _m("resilience.feeder_retries", "counter", "resilience",
       "Feeder stage retries after an injected/real error."),
    _m("resilience.worker_resets", "counter", "resilience",
       "Divergent workers re-adopted from the center."),
    _m("resilience.ckpt_corrupt_detected", "counter", "resilience",
       "Checkpoint integrity failures detected by digest sidecars."),
    _m("resilience.ckpt_fallback_steps", "counter", "resilience",
       "Restores that fell back to a previous checkpoint step."),
    _m("resilience.supervisor_retries", "counter", "resilience",
       "Supervisor retry-with-resume attempts."),
    _m("resilience.supervisor_exhausted", "counter", "resilience",
       "Supervisor retry budgets exhausted."),
    _m("resilience.host_restarts", "counter", "resilience",
       "Per-host restarts by Job.supervise."),
    _m("resilience.straggler_kills", "counter", "resilience",
       "Straggler hosts killed by Job.supervise."),
    _m("resilience.ps_restarts", "counter", "resilience",
       "Parameter-server restarts by Job.supervise."),
    _m("resilience.liveness_kills", "counter", "resilience",
       "Hosts killed for failing the liveness contract."),
    _m("resilience.faults_injected", "counter", "resilience",
       "Faults fired from the active DKTPU_FAULTS plan."),
    _m("resilience.supervised_train", "span", "resilience",
       "One supervised training attempt (retries nest as new spans)."),
    # -- networked PS -----------------------------------------------------
    _m("netps.commits", "counter", "netps",
       "Commits folded into the center (exactly-once evidence)."),
    _m("netps.commits_deduped", "counter", "netps",
       "Retransmitted commits answered from the dedup table."),
    _m("netps.bytes_sent", "counter", "netps",
       "Wire bytes sent (both sides count their own)."),
    _m("netps.bytes_received", "counter", "netps",
       "Wire bytes received."),
    _m("netps.bytes_precompress", "counter", "netps",
       "Commit bytes before the DKTPU_NET_COMPRESS codec."),
    _m("netps.protocol_errors", "counter", "netps",
       "Frames rejected by magic/crc/size/spec checks."),
    _m("netps.retries", "counter", "netps",
       "RPC retries after a retryable failure."),
    _m("netps.reconnects", "counter", "netps",
       "Client reconnects after a dead connection."),
    _m("netps.rejoins", "counter", "netps",
       "Evicted workers re-admitted mid-run."),
    _m("netps.evictions", "counter", "netps",
       "Workers evicted on lease expiry."),
    _m("netps.revocations", "counter", "netps",
       "Administrative lease revocations (the preemption primitive)."),
    _m("netps.probes", "counter", "netps",
       "Tuner probe round trips answered."),
    _m("netps.rpc_failures", "counter", "netps",
       "RPC attempts that failed (timeout, connection loss, framing)."),
    _m("netps.stale_replies", "counter", "netps",
       "Duplicate replies discarded by the request-id echo."),
    _m("netps.shm_upgrades", "counter", "netps",
       "Routine post-join TCP-to-ring transport upgrades."),
    _m("netps.shm_fallbacks", "counter", "netps",
       "Mid-run ring-to-TCP downgrades after ring failures."),
    _m("netps.mesh.upgrades", "counter", "netps",
       "Post-join upgrades onto the same-runtime device-mesh dispatch."),
    _m("netps.mesh.folds", "counter", "netps",
       "Commits folded by the device-resident center's collective."),
    _m("netps.mesh.demotions", "counter", "netps",
       "Mesh-to-shm/TCP demotions (device loss, mesh_down, gone peer)."),
    _m("netps.endpoint_walks", "counter", "netps",
       "Endpoint-list failover steps taken by clients."),
    _m("netps.pull_torn_retries", "counter", "netps",
       "Striped pulls re-read across a concurrent fold."),
    _m("netps.fold.tensors_per_sec", "gauge", "netps",
       "Fold throughput of the most recent commit."),
    _m("netps.overlap.hidden_fraction", "gauge", "netps",
       "1 - visible comms wait / total comms time (overlap win)."),
    _m("netps.commit.staleness", "histogram", "netps",
       "Realized staleness the server charged per commit."),
    _m("netps.remote_train", "span", "netps",
       "The remote worker loop, end to end."),
    _m("netps.server.", "span", "netps",
       "Server-side per-op handler latency; suffix = op + transport "
       "dialect.", dynamic=True),
    _m("netps.rpc.", "span", "netps",
       "Client-side per-op RPC latency; suffix = op, stripe, dialect.",
       dynamic=True),
    _m("netps.hier.fan_in", "gauge", "netps",
       "Per-host aggregator worker fan-in."),
    _m("netps.hier.worker_commits", "counter", "netps",
       "Worker commits absorbed by per-host aggregators."),
    _m("netps.hier.combined_commits", "counter", "netps",
       "Combined commits forwarded upstream (ratio = ingress cut)."),
    _m("netps.hier.lost_windows", "counter", "netps",
       "Combined windows lost to an upstream eviction."),
    _m("netps.tree.buffered_windows", "gauge", "netps",
       "Combined windows riding out a dark uplink in a tree node."),
    _m("netps.tree.drained_windows", "counter", "netps",
       "Buffered windows drained in-order after an uplink heal."),
    _m("netps.tree.dropped_windows", "counter", "netps",
       "Windows dropped (typed) past the tree ride-through bound."),
    _m("netps.tree.dropped_commits", "counter", "netps",
       "Constituent worker commits inside dropped tree windows."),
    _m("netps.tree.silent_loss", "gauge", "netps",
       "Tree window-conservation residual; nonzero = a silent loss."),
    _m("netps.tree.link_downs", "counter", "netps",
       "Injected link_down/link_flap outages consumed by tree uplinks."),
    _m("netps.tree.link_demotions", "counter", "netps",
       "Tree uplinks demoted to plain TCP after failure streaks."),
    _m("netps.tree.link_promotions", "counter", "netps",
       "Demoted tree uplinks renegotiated back up."),
    _m("netps.tree.codec_negotiations", "counter", "netps",
       "Per-link codec picks (pinned, probed, or default)."),
    _m("netps.recovery.snapshots", "gauge", "netps",
       "Snapshots written by the live server."),
    _m("netps.recovery.snapshot_loads", "counter", "netps",
       "Snapshots loaded on recovery (newest-intact-first)."),
    _m("netps.recovery.snapshots_rejected", "counter", "netps",
       "Corrupt snapshots rejected during the recovery walk."),
    _m("netps.recovery.replayed_commits", "counter", "netps",
       "Journal records replayed onto the recovered snapshot."),
    _m("netps.recovery.journals_truncated", "counter", "netps",
       "Crash-torn journal tails dropped on recovery."),
    _m("netps.recovery.journal_gaps", "counter", "netps",
       "Interior journal damage detected on recovery."),
    _m("netps.failover.promotions", "counter", "netps",
       "Warm standbys promoted to primary."),
    _m("netps.failover.replicated_commits", "counter", "netps",
       "Journal records applied by tailing standbys."),
    _m("netps.failover.replicate_rejected", "counter", "netps",
       "Replication records a standby refused (lineage change)."),
    _m("netps.failover.snapshot_syncs", "counter", "netps",
       "Full state syncs answered to fresh/behind standbys."),
    _m("netps.failover.fenced_commits", "counter", "netps",
       "Stale-epoch commits rejected (zero-stale-epoch-folds proof)."),
    _m("netps.failover.fences_accepted", "counter", "netps",
       "Fence ops accepted (a zombie ex-primary stopped folding)."),
    _m("netps.shard.count", "gauge", "netps",
       "Shards in the deployed partition plan."),
    _m("netps.shard.skew", "gauge", "netps",
       "Planned byte skew across shards."),
    _m("netps.shard.partial_commits", "counter", "netps",
       "Commits reconciled by same-seq retransmit after shard failure."),
    _m("netps.shard.folds.", "counter", "netps",
       "Per-shard fold count; suffix = shard index.", dynamic=True),
    _m("netps.shard.bytes.", "counter", "netps",
       "Per-shard fold bytes; suffix = shard index.", dynamic=True),
    # -- fleet control plane (suffix = .tenant.job attribution) -----------
    _m("fleet.submitted", "counter", "fleet",
       "Jobs submitted to the scheduler."),
    _m("fleet.liveness_requeues", "counter", "fleet",
       "Jobs requeued by the liveness sentinel."),
    _m("fleet.serving_drains_refused", "counter", "fleet",
       "Full-drain preemptions refused by the serving floor."),
    _m("fleet.commits", "counter", "fleet",
       "Per-job applied commits; suffix = tenant.job.", dynamic=True),
    _m("fleet.round", "span", "fleet",
       "Per-job worker round; suffix = tenant.job.", dynamic=True),
    _m("fleet.preemptions.", "counter", "fleet",
       "Per-tenant preemptions.", dynamic=True),
    _m("fleet.shrinks.", "counter", "fleet",
       "Per-tenant gang shrinks.", dynamic=True),
    _m("fleet.expands.", "counter", "fleet",
       "Per-tenant gang re-expansions.", dynamic=True),
    _m("fleet.restarts.", "counter", "fleet",
       "Per-tenant crashed-worker restarts.", dynamic=True),
    _m("fleet.placements.", "counter", "fleet",
       "Per-tenant gang placements.", dynamic=True),
    _m("fleet.granted.", "gauge", "fleet",
       "Per-tenant slots currently granted.", dynamic=True),
    _m("fleet.preempt_debt.", "gauge", "fleet",
       "Per-tenant outstanding preemption debt.", dynamic=True),
    _m("fleet.staleness_mean", "gauge", "fleet",
       "Per-job mean staleness; suffix = tenant.job.", dynamic=True),
    _m("fleet.staleness_max", "gauge", "fleet",
       "Per-job max staleness; suffix = tenant.job.", dynamic=True),
    # -- serving plane ----------------------------------------------------
    _m("serving.accepted", "counter", "serving",
       "Requests admitted past the queue bound."),
    _m("serving.answered", "counter", "serving",
       "Accepted requests answered (result or typed error)."),
    _m("serving.shed", "counter", "serving",
       "Requests shed before admission (typed overloaded reply)."),
    _m("serving.deadline_drops", "counter", "serving",
       "Accepted requests answered with the typed deadline error."),
    _m("serving.queue_depth", "gauge", "serving",
       "Admission queue depth."),
    _m("serving.latency", "histogram", "serving",
       "Admission-to-reply latency (report CLI derives p50/p99)."),
    _m("serving.batches", "counter", "serving",
       "Micro-batches dispatched."),
    _m("serving.batched_rows", "counter", "serving",
       "Rows dispatched inside micro-batches."),
    _m("serving.padded_rows", "counter", "serving",
       "Bucket-padding rows (overhead = padded/batched)."),
    _m("serving.dispatch", "span", "serving",
       "Micro-batch dispatch latency."),
    _m("serving.retrace_after_warmup", "counter", "serving",
       "Post-warmup retraces (must stay 0)."),
    _m("serving.swaps", "counter", "serving",
       "Hot-swaps to a newer verified checkpoint."),
    _m("serving.swap_failures", "counter", "serving",
       "Candidate checkpoints rejected by verify/warmup."),
    _m("serving.swap_rejected_regression", "counter", "serving",
       "Candidates rejected by the regression gate."),
    _m("serving.freshness", "histogram", "serving",
       "Served-model staleness at swap time."),
    _m("serving.freshness_s", "gauge", "serving",
       "Seconds between served model's data and now."),
    _m("serving.client_failovers", "counter", "serving",
       "Client endpoint walks to a surviving replica."),
    _m("serving.conn_errors", "counter", "serving",
       "Serving client transport errors."),
    # -- streaming continual training -------------------------------------
    _m("stream.items_read", "counter", "streaming",
       "Records read from the stream source."),
    _m("stream.items_committed", "counter", "streaming",
       "Records provably folded (journal-committed); may carry a "
       "per-job suffix.", dynamic=True),
    _m("stream.requeued", "counter", "streaming",
       "Records re-queued after a failed commit attempt.", dynamic=True),
    _m("stream.source_reconnects", "counter", "streaming",
       "Stream source reconnects after a gap/error."),
    _m("stream.drift_injected", "counter", "streaming",
       "Injected concept-drift triggers consumed."),
    _m("stream.drift_events", "counter", "streaming",
       "Drift divergence pages fired by windowed eval."),
    _m("stream.offset_lag", "gauge", "streaming",
       "Records read but not yet journal-committed."),
    _m("stream.eval.loss_fast", "gauge", "streaming",
       "Fast-window eval loss (drift detector input)."),
    _m("stream.eval.loss_slow", "gauge", "streaming",
       "Slow-window eval loss (drift detector baseline)."),
    _m("stream.candidate_loss", "gauge", "streaming",
       "Candidate checkpoint eval loss at the regression gate."),
    _m("stream.recovery_seconds", "gauge", "streaming",
       "Post-drift recovery time to the pre-drift loss band."),
    _m("stream.staleness_mean", "gauge", "streaming",
       "Mean staleness of streaming commits.", dynamic=True),
    _m("stream.checkpoint", "span", "streaming",
       "Streaming checkpoint write (journal + meta + arrays)."),
    _m("stream.item", "span", "streaming",
       "One record's train+commit; suffix = worker slot.", dynamic=True),
    # -- self-tuning data plane -------------------------------------------
    _m("tuner.probes", "counter", "tuner",
       "Join-time micro-A/B probes sent."),
    _m("tuner.decisions", "counter", "tuner",
       "Knob decisions adopted."),
    _m("tuner.decision.", "counter", "tuner",
       "Adopted decisions; suffix = knob name.", dynamic=True),
    _m("tuner.deferred", "counter", "tuner",
       "Decisions deferred by the hysteresis window."),
    _m("tuner.floor_violations", "counter", "tuner",
       "Throughput floor violations observed."),
    _m("tuner.oscillation_fallbacks", "counter", "tuner",
       "Knobs frozen after oscillating decisions."),
    _m("tuner.expand_blocked", "counter", "tuner",
       "Fleet expansions blocked by marginal-throughput evidence."),
    _m("tuner.knob_warnings", "counter", "tuner",
       "Client-side warnings for rejected knob applications."),
    _m("tuner.knob.codec", "gauge", "tuner",
       "Active codec knob (index into the codec list)."),
    _m("tuner.knob.inflight", "gauge", "tuner",
       "Active in-flight window knob."),
    _m("tuner.knob.shards", "gauge", "tuner",
       "Active stripe-count knob."),
    _m("tuner.knob.", "gauge", "tuner",
       "Active value per tuned knob.", dynamic=True),
    _m("tuner.marginal_tput.", "gauge", "tuner",
       "Marginal throughput per added worker; suffix = job.",
       dynamic=True),
    # -- health / vitals --------------------------------------------------
    _m("health.alerts_fired", "counter", "health",
       "SLO burn-rate alerts fired."),
    _m("health.alerts_cleared", "counter", "health",
       "SLO alerts cleared after recovery."),
    _m("runtime.rss_mb", "gauge", "runtime",
       "Process resident set size, MB."),
    _m("runtime.open_fds", "gauge", "runtime",
       "Open file descriptors."),
    _m("device.bytes_in_use", "gauge", "runtime",
       "Accelerator bytes in use (when the backend reports it)."),
]

#: name -> Metric; the declaration above is the single source of truth.
METRIC_REGISTRY: Dict[str, Metric] = {}
for _entry in _METRICS:
    if _entry.name in METRIC_REGISTRY:
        raise ValueError(f"duplicate metric declaration {_entry.name!r}")
    METRIC_REGISTRY[_entry.name] = _entry
del _entry

#: category names in declaration order (the docs table order).
CATEGORIES = tuple(dict.fromkeys(m.category for m in _METRICS))


def iter_metrics(category: Optional[str] = None) -> Iterable[Metric]:
    if category is not None and category not in CATEGORIES:
        raise ValueError(f"unknown metric category {category!r}; "
                         f"known: {list(CATEGORIES)}")
    for m in _METRICS:
        if category is None or m.category == category:
            yield m


def declared(kind: str, name: str) -> bool:
    """Is the exact literal ``name`` a declared ``kind`` metric?"""
    m = METRIC_REGISTRY.get(name)
    if m is not None and m.kind == kind:
        return True
    return any(m.dynamic and m.kind == kind and name.startswith(m.name)
               for m in _METRICS)


def declared_prefix(kind: str, leading: str) -> bool:
    """Is an f-string with constant prefix ``leading`` compatible with a
    declared dynamic metric of ``kind``? (The suffix is computed at
    runtime, so the check is prefix-compatibility both ways.)"""
    return any(m.dynamic and m.kind == kind
               and (leading.startswith(m.name)
                    or m.name.startswith(leading))
               for m in _METRICS)


def render_metric_table(category: Optional[str] = None) -> str:
    """The markdown metric table for ``category`` (None = all, with a
    category column). Injected between ``<!-- dk-metric:begin ... -->`` /
    ``<!-- dk-metric:end -->`` markers by ``--write-metric-docs``; DK602
    fails CI when a docs table no longer matches this rendering."""
    rows = list(iter_metrics(category))
    with_cat = category is None
    head = "| Name | Kind | Description |"
    sep = "|---|---|---|"
    if with_cat:
        head = "| Name | Kind | Category | Description |"
        sep = "|---|---|---|---|"
    out = [head, sep]
    for m in rows:
        name = f"`{m.name}*`" if m.dynamic else f"`{m.name}`"
        cells = [name, m.kind]
        if with_cat:
            cells.append(m.category)
        cells.append(m.doc)
        out.append("| " + " | ".join(cells) + " |")
    return "\n".join(out)


def splice_metric_docs(text: str, path_hint: str = "") -> str:
    """Replace every ``<!-- dk-metric:begin [category=X] -->`` ...
    ``<!-- dk-metric:end -->`` block in ``text`` with the freshly
    rendered table for that category."""
    import re

    def sub(m) -> str:
        category = m.group("cat") or None
        return (m.group("open") + "\n" + render_metric_table(category)
                + "\n" + m.group("close"))

    pat = re.compile(
        r"(?P<open><!-- dk-metric:begin(?: category=(?P<cat>[\w-]+))? -->)"
        r".*?(?P<close><!-- dk-metric:end -->)",
        re.DOTALL)
    out, n = pat.subn(sub, text)
    if n == 0 and path_hint:
        raise ValueError(f"no dk-metric marker block found in {path_hint}")
    return out
