"""Process-vitals sampler: periodic runtime gauges feeding the hub.

A tiny background thread that samples, every ``DKTPU_VITALS_S`` seconds:

* ``runtime.rss_mb`` — resident set size (``/proc/self/status`` VmRSS,
  falling back to ``resource.getrusage`` off Linux);
* ``runtime.open_fds`` — open file descriptors (``/proc/self/fd``);
* ``device.bytes_in_use`` — accelerator memory from jax's
  ``device.memory_stats()``, only when jax is already imported *and*
  sees a device that reports stats (never imports jax itself — the
  telemetry layer stays contractually jax-free).

The gauges land in the ordinary telemetry registry, so they ride the
stats op for free and the health plane's ``MetricsHub`` picks them up on
the next scrape. Behind the master telemetry kill-switch: with
``DKTPU_TELEMETRY=0`` or a zero interval, :func:`start_vitals` is a
no-op.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

from distkeras_tpu import telemetry
from distkeras_tpu.runtime.config import env_float


def sample_vitals() -> dict:
    """One vitals sample, also written to the telemetry gauges. Split
    out from the loop so tests (and curious callers) can sample
    synchronously."""
    out = {}
    rss = _rss_mb()
    if rss is not None:
        telemetry.gauge("runtime.rss_mb").set(rss)
        out["runtime.rss_mb"] = rss
    fds = _open_fds()
    if fds is not None:
        telemetry.gauge("runtime.open_fds").set(float(fds))
        out["runtime.open_fds"] = float(fds)
    dev = _device_bytes_in_use()
    if dev is not None:
        telemetry.gauge("device.bytes_in_use").set(float(dev))
        out["device.bytes_in_use"] = float(dev)
    return out


def _rss_mb() -> Optional[float]:
    try:
        with open("/proc/self/status", "r", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0  # kB -> MiB
    except OSError:
        pass
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # Linux reports kB, macOS bytes; either way it's a usable gauge.
        return ru / 1024.0 if sys.platform.startswith("linux") else \
            ru / (1024.0 * 1024.0)
    except Exception:
        return None


def _open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def _device_bytes_in_use() -> Optional[int]:
    jax = sys.modules.get("jax")
    if jax is None:  # vitals never forces the jax import
        return None
    try:
        for dev in jax.devices():
            stats = getattr(dev, "memory_stats", None)
            stats = stats() if callable(stats) else None
            if stats and "bytes_in_use" in stats:
                return int(stats["bytes_in_use"])
    except Exception:
        return None
    return None


_lock = threading.Lock()
_thread: Optional[threading.Thread] = None
_stop: Optional[threading.Event] = None


def start_vitals(interval_s: Optional[float] = None) -> bool:
    """Start the sampler if telemetry is on and the interval is > 0
    (default from ``DKTPU_VITALS_S``). Idempotent; returns whether a
    sampler is running after the call."""
    global _thread, _stop
    interval = (env_float("DKTPU_VITALS_S") if interval_s is None
                else float(interval_s))
    if not telemetry.enabled() or not interval or interval <= 0:
        return False
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        stop = threading.Event()

        def run() -> None:
            while not stop.is_set():
                try:
                    sample_vitals()
                except Exception:
                    pass
                stop.wait(interval)

        _stop = stop
        _thread = threading.Thread(target=run, name="dktpu-vitals",
                                   daemon=True)
        _thread.start()
    return True


def stop_vitals() -> None:
    global _thread, _stop
    with _lock:
        thread, stop = _thread, _stop
        _thread = _stop = None
    if stop is not None:
        stop.set()
    if thread is not None:
        thread.join(timeout=5.0)
