"""Unified telemetry: spans, counters, gauges, exporters, and the run report.

The single instrumentation layer every execution path reports through
(ISSUE 1): engine run loops (dispatch/retire latency per block mode), the
data plane (queue depth, input-stall time), inference (chunk latency,
pending rows, per-shard skew), and the disciplines' staleness schedule.

Usage — the ambient registry (per-process aggregation)::

    from distkeras_tpu import telemetry

    with telemetry.span("dispatch"):
        ...                                   # nested spans -> "a/b" paths
    telemetry.counter("rounds").add(1)
    telemetry.gauge("queue_depth").set(3)

    telemetry.write_jsonl(telemetry.get(), "run.jsonl")   # append-only JSONL
    print(telemetry.prometheus_text(telemetry.get()))     # Prometheus dump

Disable with ``DKTPU_TELEMETRY=0`` (all calls become no-ops). Render a
report with ``python -m distkeras_tpu.telemetry report run.jsonl``.
"""

from __future__ import annotations

from distkeras_tpu.telemetry.core import (
    Counter,
    Gauge,
    Histogram,
    Telemetry,
    current_labels,
    enabled,
    get,
    label_suffix,
    reset,
    sanitize_label,
    scoped_labels,
)
from distkeras_tpu.telemetry.exporters import (
    parse_prometheus,
    prometheus_text,
    read_jsonl,
    write_jsonl,
)
from distkeras_tpu.telemetry.training import (
    DisciplineMonitor,
    dynsgd_scales,
    flag_stragglers,
    staleness_schedule,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "Telemetry",
    "enabled", "get", "reset",
    "span", "counter", "gauge", "histogram", "event",
    "scoped_labels", "current_labels", "label_suffix", "sanitize_label",
    "write_jsonl", "read_jsonl", "prometheus_text", "parse_prometheus",
    "DisciplineMonitor", "flag_stragglers", "staleness_schedule",
    "dynsgd_scales",
]


# -- module-level shorthands routing to the ambient registry ---------------
def span(name: str):
    return get().span(name)


def counter(name: str):
    return get().counter(name)


def gauge(name: str):
    return get().gauge(name)


def histogram(name: str):
    return get().histogram(name)


def event(kind: str, fields=None):
    return get().event(kind, fields)
