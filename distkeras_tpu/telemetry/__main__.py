"""CLI entry point: ``python -m distkeras_tpu.telemetry report run.jsonl``."""

import sys

from distkeras_tpu.telemetry.report import main

sys.exit(main())
