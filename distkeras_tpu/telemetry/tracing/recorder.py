"""The crash flight recorder: a bounded ring of recent evidence.

Chaos postmortems need the last few seconds of a process's life — which
spans were in flight, which faults fired, which commits folded — without
paying full-rate logging on healthy runs. Every process keeps a
``DKTPU_TRACE_RING``-bounded deque of recent telemetry events and trace
spans (fed by the telemetry core's event tap, so instrumented code needs
no second call site), and dumps it to ``flight-<role>-<pid>.jsonl`` when
something goes wrong:

* **fault injection** — ``FaultPlan._fire`` dumps BEFORE the effect, so
  even ``ps_crash``'s SIGKILL leaves evidence on disk;
* **epoch fencing** — a client whose commit/pull was fenced dumps its
  view of the discarded lineage;
* **SIGTERM** — the netps CLI's drain path dumps on the first signal;
* **unhandled crash** — :func:`install_crash_hooks` wraps
  ``sys.excepthook`` / ``threading.excepthook``.

Dumps are additive (append-mode, one ``flight_dump`` marker record per
dump) and deduplicated per reason per process, so a fault storm does not
write the same ring a hundred times.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Optional

from distkeras_tpu.runtime import config
from distkeras_tpu.telemetry.tracing import context


class FlightRecorder:
    """One bounded ring of recent records + the dump-on-fault writer."""

    def __init__(self, size: Optional[int] = None):
        if size is None:
            size = max(8, config.env_int("DKTPU_TRACE_RING"))
        self._ring: deque = deque(maxlen=int(size))
        self._lock = threading.Lock()
        self._dumped: set = set()

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    def head(self, n: int = 64) -> list:
        """The most recent ``n`` records, oldest first (the ``stats``
        op's live scrape payload)."""
        with self._lock:
            items = list(self._ring)
        return items[-int(n):]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, reason: str, once: bool = True) -> Optional[str]:
        """Write the ring to ``flight-<role>-<pid>.jsonl`` in the trace
        dir (falling back to the PS state dir; no dir = no dump). Returns
        the path, or None when skipped/deduped. Best-effort: a dump must
        never mask the failure that triggered it."""
        d = context.trace_dir()
        if not d:
            return None
        with self._lock:
            if once and reason in self._dumped:
                return None
            self._dumped.add(reason)
            items = list(self._ring)
        path = os.path.join(
            d, f"flight-{context.role()}-{os.getpid()}.jsonl")
        try:
            os.makedirs(d, exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(context.process_info_record()) + "\n")
                f.write(json.dumps({"kind": "flight_dump",
                                    "reason": str(reason),
                                    "ts": time.time(),
                                    "records": len(items)}) + "\n")
                for rec in items:
                    f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except (OSError, TypeError, ValueError):
            return None
        return path


_RING: Optional[FlightRecorder] = None
_RING_LOCK = threading.Lock()


def get_ring() -> FlightRecorder:
    """The process-global flight recorder (created on first touch)."""
    global _RING
    if _RING is None:
        with _RING_LOCK:
            if _RING is None:
                _RING = FlightRecorder()
    return _RING


def ring_head(n: int = 64) -> list:
    """The global ring's most recent ``n`` records (empty before any
    activity — the accessor never creates work)."""
    if _RING is None:
        return []
    return _RING.head(n)


def flight_dump(reason: str, once: bool = True) -> Optional[str]:
    """Dump the global ring (no-op with tracing off — the ring is only
    fed when tracing is on, so there would be nothing to say)."""
    if not context.enabled():
        return None
    return get_ring().dump(reason, once=once)


def _tap(rec: dict) -> None:
    """The telemetry core's event tap: every recorded event (trace spans
    included — they ride the event stream) lands in the ring when tracing
    is on. Installed once at package import; the enabled() check keeps
    the off-path to one dict lookup."""
    if context.enabled():
        get_ring().record(rec)


_HOOKS = {"installed": False}


def install_crash_hooks() -> None:
    """Wrap ``sys.excepthook``/``threading.excepthook`` to flight-dump on
    any unhandled exception before the previous hook runs (idempotent;
    long-running entry points — the netps CLI, the serving frontend —
    call this at startup)."""
    if _HOOKS["installed"]:
        return
    _HOOKS["installed"] = True
    prev_sys = sys.excepthook
    prev_thread = threading.excepthook

    def _sys_hook(exc_type, exc, tb):
        try:
            flight_dump(f"crash:{exc_type.__name__}", once=False)
        except Exception:  # noqa: BLE001 - never mask the real crash
            pass
        prev_sys(exc_type, exc, tb)

    def _thread_hook(args):
        try:
            flight_dump(f"crash:{args.exc_type.__name__}", once=False)
        except Exception:  # noqa: BLE001 - never mask the real crash
            pass
        prev_thread(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _thread_hook


def _reset() -> None:
    """Tests only: fresh ring + dump dedup."""
    global _RING
    with _RING_LOCK:
        _RING = None
