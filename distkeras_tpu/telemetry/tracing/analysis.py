"""Critical-path analysis over collector-merged trace streams.

Input is the flat record list :meth:`~distkeras_tpu.telemetry.tracing.
collector.TelemetryCollector.records` produces (clock-aligned, deduped,
identity-stamped); output is the structured trace report ``python -m
distkeras_tpu.telemetry report --trace`` renders:

* per-commit **critical-path breakdown** — every commit trace decomposed
  into the lifecycle segments (encode / wire / queue-behind-fold / fold /
  fsync / replicate / ACK), with exact p50/p99 per segment (computed from
  the full duration lists, not histogram buckets — the collector already
  holds every span);
* **completeness** — a commit trace is complete when it carries every
  segment the *deployment* produces: encode/wire/queue/fold/ack always,
  fsync only when the run journaled (any fsync span exists in the merged
  stream), replicate only when a standby tailed it. Config-awareness
  keeps a memory-only run from reporting 0% complete;
* **slowest-trace exemplars** — the top traces by end-to-end duration,
  each with its own segment split (the "what do I look at first" table);
* **chaos correlation** — fault/eviction/rejoin/promotion/flight-dump
  events that overlap the slow tail (> p99 end-to-end), so an injected
  ``ps_crash`` shows up next to the commits it stalled;
* **orphan + clock checks** — traces with server-side spans but no
  client root (a crashed client, or a propagation bug), and traces whose
  child spans start before their root even after alignment (a clock
  estimate that went wrong).

Pure stdlib over plain dicts — importable wherever the collector is.
"""

from __future__ import annotations

from typing import Iterable, Optional

from distkeras_tpu.telemetry.tracing.context import SPAN_KIND

#: span-name -> segment label for the commit lifecycle, in path order.
COMMIT_SEGMENTS = {
    "commit.encode": "encode",
    "commit.wire": "wire",
    "commit.queue": "queue",
    "commit.fold": "fold",
    "commit.fsync": "fsync",
    "commit.replicate": "replicate",
    "commit.ack": "ack",
}
SEGMENT_ORDER = ("encode", "wire", "queue", "fold", "fsync",
                 "replicate", "ack")
#: segments every deployment produces; fsync/replicate join the required
#: set only when the merged stream shows the run actually had them.
BASE_REQUIRED = frozenset({"encode", "wire", "queue", "fold", "ack"})
#: span names that root a trace client-side — a trace containing none of
#: these but some server-side segment is an *orphan* (its origin process
#: never wrote its half, or propagation broke).
ROOT_NAMES = frozenset({"commit", "pull", "serve.request", "hier.flush",
                        "tuner.retune"})
#: event kinds correlated against the slow tail.
CHAOS_KINDS = frozenset({"fault_injected", "flight_dump", "netps_eviction",
                         "netps_rejoin", "netps_promotion",
                         "netps_fenced", "serving_revocation",
                         "netps_lost_window", "netps_tree_window_drop",
                         "netps_tree_link_down"})
#: alignment slack (seconds) before a child-before-root timestamp counts
#: as a clock violation — min-RTT offset estimates are good to ~rtt/2.
SKEW_SLACK_S = 0.005
#: sample floor below which a fitted segment distribution is flagged
#: unreliable (lognormal mu/sigma from < this many points is noise).
MIN_FIT_SAMPLES = 8


def spans_of(records: Iterable[dict]) -> list[dict]:
    """Just the span records of a merged stream."""
    return [r for r in records if r.get("kind") == SPAN_KIND
            and r.get("trace")]


def assemble_traces(records: Iterable[dict]) -> dict:
    """Group spans by trace id: ``{trace: {"spans": [...], "root": ...}}``.
    The root is the trace's parentless span (the client scope that minted
    the id); None for orphans."""
    traces: dict = {}
    for rec in spans_of(records):
        t = traces.setdefault(rec["trace"], {"spans": [], "root": None})
        t["spans"].append(rec)
        if not rec.get("parent") and rec.get("name") in ROOT_NAMES:
            # Two parentless spans can share a trace (a standby's
            # replicate span carries no parent by design) — the named
            # root wins; first one sticks if a stream was double-merged.
            if t["root"] is None:
                t["root"] = rec
    return traces


def _segment_durs(spans: list[dict]) -> dict:
    """Per-segment duration of one trace. Fan-out segments (a striped
    commit sends one ``commit.wire`` per shard, folded on N servers) take
    the MAX across their spans — stripes run in parallel and the slowest
    one gates the commit; summing would bill serial time the client never
    waited."""
    durs: dict = {}
    for s in spans:
        seg = COMMIT_SEGMENTS.get(s.get("name", ""))
        if seg is None:
            continue
        d = float(s.get("dur") or 0.0)
        durs[seg] = max(durs.get(seg, 0.0), d)
    return durs


def _quantile(sorted_vals: list, q: float) -> float:
    """Exact nearest-rank quantile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def commit_paths(records: Iterable[dict]) -> list[tuple]:
    """Every commit trace's critical path: ``[(trace_id, root_span,
    segment_durs, end_to_end_s)]`` in trace-id order. A commit trace is
    one rooted by a ``commit`` span, or a ``hier.flush`` that carries a
    nested ``commit`` (the aggregator's upstream hop). This is the ONE
    commit-selection rule — :func:`trace_report` and
    :func:`segment_model` both read it, so the report and the simulator
    calibrate from the same population."""
    out = []
    for tid, t in sorted(assemble_traces(records).items()):
        root = t["root"]
        if root is None:
            continue
        name = root.get("name")
        if name == "commit" or (name == "hier.flush" and any(
                s.get("name") == "commit" for s in t["spans"])):
            out.append((tid, root, _segment_durs(t["spans"]),
                        float(root.get("dur") or 0.0)))
    return out


def _lognorm_fit(vals: list) -> Optional[dict]:
    """Method-of-moments-in-log-space lognormal fit over the positive
    samples (a zero-duration span carries no timing information)."""
    import math

    pos = [v for v in vals if v > 0.0]
    if not pos:
        return None
    logs = [math.log(v) for v in pos]
    mu = sum(logs) / len(logs)
    var = sum((x - mu) ** 2 for x in logs) / len(logs)
    return {"mu": mu, "sigma": math.sqrt(var), "samples": len(pos)}


def segment_model(records: Optional[list] = None, *,
                  commits: Optional[list] = None,
                  min_samples: int = MIN_FIT_SAMPLES) -> dict:
    """The per-segment quantile extraction + fitted latency model — the
    ONE implementation behind both the ``--trace`` report's segment table
    and the fleet simulator's calibration (``distkeras_tpu.sim``), so the
    two can never drift.

    Pass a collector-merged record list, or a precomputed
    :func:`commit_paths` list via ``commits=``. Returns::

        {"segments": {seg: {count, p50_s, p99_s, max_s, total_s, mean_s,
                            lognorm: {mu, sigma, samples} | None,
                            fit_ok: bool}},
         "e2e": {count, p50_s, p99_s, mean_s} | None,
         "commits": N, "min_samples": min_samples,
         "warnings": ["segment 'x' has 3 samples (< 8) ..."]}

    ``lognorm`` is a log-space moment fit (duration distributions are
    multiplicative: a segment is a product of per-byte / per-row costs),
    good enough to resample from; ``fit_ok`` is False when the segment
    has fewer than ``min_samples`` positive samples."""
    if commits is None:
        commits = commit_paths(records or [])
    seg_durs: dict = {seg: [] for seg in SEGMENT_ORDER}
    for _tid, _root, durs, _e2e in commits:
        for seg, d in durs.items():
            seg_durs[seg].append(d)

    segments: dict = {}
    warnings: list[str] = []
    for seg in SEGMENT_ORDER:
        vals = sorted(seg_durs[seg])
        if not vals:
            continue
        fit = _lognorm_fit(vals)
        ok = bool(fit and fit["samples"] >= min_samples)
        if not ok:
            n = fit["samples"] if fit else 0
            warnings.append(
                f"segment {seg!r} has {n} positive sample(s) "
                f"(< {min_samples}) — fit unreliable")
        segments[seg] = {
            "count": len(vals),
            "p50_s": _quantile(vals, 0.50),
            "p99_s": _quantile(vals, 0.99),
            "max_s": vals[-1],
            "total_s": sum(vals),
            "mean_s": sum(vals) / len(vals),
            "lognorm": fit,
            "fit_ok": ok,
        }

    e2e_sorted = sorted(e for _t, _r, _d, e in commits)
    e2e = None
    if e2e_sorted:
        e2e = {"count": len(e2e_sorted),
               "p50_s": _quantile(e2e_sorted, 0.50),
               "p99_s": _quantile(e2e_sorted, 0.99),
               "mean_s": sum(e2e_sorted) / len(e2e_sorted)}
    return {"segments": segments, "e2e": e2e, "commits": len(commits),
            "min_samples": min_samples, "warnings": warnings}


def required_segments(all_spans: list[dict]) -> frozenset:
    """The config-aware completeness bar for this stream."""
    names = {s.get("name") for s in all_spans}
    req = set(BASE_REQUIRED)
    if "commit.fsync" in names:
        req.add("fsync")
    if "commit.replicate" in names:
        req.add("replicate")
    return frozenset(req)


def _skew_violation(root: dict, spans: list[dict]) -> bool:
    """Whether any child starts before the root after alignment (beyond
    the slack an rtt/2-quality offset estimate legitimately leaves)."""
    r0 = float(root.get("t0") or 0.0)
    return any(float(s.get("t0") or 0.0) < r0 - SKEW_SLACK_S
               for s in spans if s is not root)


def trace_report(records: list[dict]) -> dict:
    """The structured ``--trace`` report over one merged record list."""
    all_spans = spans_of(records)
    traces = assemble_traces(records)
    required = required_segments(all_spans)

    orphans: list[str] = []
    skew_violations = 0
    kinds = {"pull": 0, "serve.request": 0, "hier.flush": 0,
             "tuner.retune": 0}
    for tid, t in sorted(traces.items()):
        root = t["root"]
        if root is None:
            # Server-side segments with no client half.
            if any(s.get("name") in COMMIT_SEGMENTS for s in t["spans"]):
                orphans.append(tid)
            continue
        if _skew_violation(root, t["spans"]):
            skew_violations += 1
        name = root.get("name")
        if name in kinds:
            kinds[name] += 1
    commits = commit_paths(records)

    complete = [c for c in commits if required <= set(c[2])]
    # The quantile extraction + fit — shared verbatim with the simulator's
    # calibration; the report's segment table is a projection of it.
    calibration = segment_model(commits=commits)
    segments = {seg: {k: info[k] for k in
                      ("count", "p50_s", "p99_s", "max_s", "total_s")}
                for seg, info in calibration["segments"].items()}

    e2e_sorted = sorted(e for _t, _r, _d, e in commits)
    p99_e2e = _quantile(e2e_sorted, 0.99)
    slowest = sorted(commits, key=lambda c: -c[3])[:3]
    exemplars = [{
        "trace": tid,
        "dur_s": e2e,
        "t0": float(root.get("t0") or 0.0),
        "role": root.get("role"),
        "wid": root.get("wid"),
        "seq": root.get("seq"),
        "segments": {seg: durs.get(seg) for seg in SEGMENT_ORDER
                     if seg in durs},
    } for tid, root, durs, e2e in slowest]

    # Chaos correlation: disruption events overlapping the slow tail.
    slow = [(tid, float(root.get("t0") or 0.0), e2e)
            for tid, root, _d, e2e in commits if e2e > p99_e2e > 0.0]
    chaos = []
    for rec in records:
        if rec.get("kind") not in CHAOS_KINDS:
            continue
        ts = float(rec.get("ts") or 0.0)
        hit = [tid for tid, t0, e2e in slow
               if t0 - 1.0 <= ts <= t0 + e2e + 1.0]
        chaos.append({
            "kind": rec.get("kind"),
            "ts": ts,
            "role": rec.get("role"),
            "detail": rec.get("fault") or rec.get("reason")
            or rec.get("wid"),
            "slow_traces": hit,
        })

    return {
        "traces": len(traces),
        "commits": len(commits),
        "complete": len(complete),
        "completeness": (len(complete) / len(commits)) if commits else None,
        "required": sorted(required),
        "segments": segments,
        "calibration": calibration,
        "e2e_p50_s": _quantile(e2e_sorted, 0.50),
        "e2e_p99_s": p99_e2e,
        "slowest": exemplars,
        "chaos": chaos,
        "orphans": orphans,
        "skew_violations": skew_violations,
        "pulls": kinds["pull"],
        "serves": kinds["serve.request"],
        "hier_flushes": kinds["hier.flush"],
        "retunes": kinds["tuner.retune"],
        "streams": sorted({r.get("stream") for r in records
                           if r.get("stream")}),
        "processes": sorted({f"{r.get('role')}:{r.get('pid')}"
                             for r in spans_of(records)}),
    }


def _fmt_s(s: Optional[float]) -> str:
    if s is None:
        return "-"
    if s >= 1.0:
        return f"{s:.2f}s"
    if s >= 1e-3:
        return f"{s * 1e3:.2f}ms"
    return f"{s * 1e6:.0f}us"


def render_trace_report(rep: dict) -> str:
    """Human-readable rendering of :func:`trace_report` output."""
    import io

    out = io.StringIO()
    w = out.write
    w("# Trace report\n")
    w(f"streams: {len(rep['streams'])}   "
      f"processes: {', '.join(rep['processes']) or '-'}\n")
    comp = rep["completeness"]
    w(f"traces: {rep['traces']}   commit traces: {rep['commits']}   "
      f"complete: {rep['complete']}"
      + (f" ({comp * 100:.1f}%)" if comp is not None else "") + "\n")
    w(f"required segments: {', '.join(rep['required'])}\n")
    if rep["commits"]:
        w(f"end-to-end: p50 {_fmt_s(rep['e2e_p50_s'])}   "
          f"p99 {_fmt_s(rep['e2e_p99_s'])}\n")

    if rep["segments"]:
        w("\n## Critical path (per-commit segments)\n")
        w(f"{'segment':<12} {'count':>7} {'p50':>10} {'p99':>10} "
          f"{'max':>10} {'total':>10}\n")
        for seg in SEGMENT_ORDER:
            h = rep["segments"].get(seg)
            if h is None:
                continue
            w(f"{seg:<12} {h['count']:>7} {_fmt_s(h['p50_s']):>10} "
              f"{_fmt_s(h['p99_s']):>10} {_fmt_s(h['max_s']):>10} "
              f"{_fmt_s(h['total_s']):>10}\n")

    cal = rep.get("calibration") or {}
    if cal.get("segments"):
        w("\n## Calibration (fitted segment model)\n")
        w(f"{'segment':<12} {'samples':>8} {'mean':>10} "
          f"{'lognorm mu':>11} {'sigma':>8}\n")
        for seg in SEGMENT_ORDER:
            info = cal["segments"].get(seg)
            if info is None:
                continue
            fit = info.get("lognorm")
            mu = f"{fit['mu']:.3f}" if fit else "-"
            sigma = f"{fit['sigma']:.3f}" if fit else "-"
            flag = "" if info.get("fit_ok") else "  (!)"
            w(f"{seg:<12} {info['count']:>8} "
              f"{_fmt_s(info['mean_s']):>10} {mu:>11} {sigma:>8}{flag}\n")
        for warning in cal.get("warnings", ()):
            w(f"WARNING: {warning}\n")

    if rep["slowest"]:
        w("\n## Slowest commits\n")
        for ex in rep["slowest"]:
            segs = "  ".join(f"{k}={_fmt_s(v)}"
                             for k, v in ex["segments"].items())
            who = f"wid={ex['wid']} seq={ex['seq']}" \
                if ex.get("wid") is not None else ex["trace"]
            w(f"{_fmt_s(ex['dur_s']):>10}  {who}  [{segs}]\n")

    chaos = [c for c in rep.get("chaos", []) or []]
    if chaos:
        w("\n## Chaos correlation\n")
        for c in chaos:
            hit = (f" -> slow traces {', '.join(c['slow_traces'])}"
                   if c["slow_traces"] else "")
            w(f"{c['kind']} ({c.get('detail')}) at {c['ts']:.3f} "
              f"on {c.get('role')}{hit}\n")

    extras = []
    if rep["pulls"]:
        extras.append(f"pulls: {rep['pulls']}")
    if rep["serves"]:
        extras.append(f"served requests: {rep['serves']}")
    if rep["hier_flushes"]:
        extras.append(f"hier flushes: {rep['hier_flushes']}")
    if rep["retunes"]:
        extras.append(f"retunes: {rep['retunes']}")
    if extras:
        w("\n" + "   ".join(extras) + "\n")
    if rep["orphans"]:
        w(f"\nWARNING: {len(rep['orphans'])} orphan trace(s) — server "
          f"spans with no client root: "
          f"{', '.join(rep['orphans'][:8])}\n")
    if rep["skew_violations"]:
        w(f"WARNING: {rep['skew_violations']} trace(s) with child spans "
          f"before their root after alignment (clock estimate suspect)\n")
    return out.getvalue()
