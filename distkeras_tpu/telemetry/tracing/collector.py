"""The telemetry collector: N per-process streams -> one aligned timeline.

Every process in a fleet run writes its own JSONL — worker telemetry
dumps, PS/standby/shard trace streams, flight-recorder dumps — each on
its own clock, each possibly rotated into numbered generations, each
possibly crash-truncated. :class:`TelemetryCollector` merges them:

* **generations in order** — for a stream ``p``, rotated files ``p.1``,
  ``p.2``, ... are read oldest-first, then the live file;
* **torn tails tolerated** — every file goes through
  :func:`~distkeras_tpu.telemetry.exporters.read_jsonl`, whose contract
  (silent torn final line, warned interior damage) is exactly what a
  SIGKILL'd process's stream needs;
* **clock alignment** — each stream's best ``process_info`` record (the
  min-rtt NTP estimate from ``tracing/clock.py``) supplies the offset
  added to every ``ts``/``t0`` in that stream, putting all streams on the
  PS reference clock;
* **identity stamping** — records inherit their stream's
  ``host``/``pid``/``role`` so the report can attribute any line;
* **span dedup** — a span can legitimately appear twice (the telemetry
  event dump AND the trace stream); ``(trace, span)`` ids keep exactly
  one.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Iterable, Optional

from distkeras_tpu.telemetry.exporters import read_jsonl
from distkeras_tpu.telemetry.tracing.context import (PROCESS_INFO_KIND,
                                                     SPAN_KIND)


def generations(path: str) -> list[str]:
    """``path``'s rotated generations oldest-first, live file last (only
    files that exist; a never-rotated stream is just ``[path]``)."""
    out = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        out.append(f"{path}.{n}")
        n += 1
    if os.path.exists(path):
        out.append(path)
    return out


def _best_info(records: list[dict]) -> dict:
    """The stream's authoritative ``process_info``: the min-rtt estimate
    (ties -> latest), falling back to the last identity record seen."""
    best: Optional[dict] = None
    for rec in records:
        if rec.get("kind") != PROCESS_INFO_KIND:
            continue
        if best is None:
            best = rec
            continue
        r_new, r_old = rec.get("clock_rtt_s"), best.get("clock_rtt_s")
        if r_old is None or (r_new is not None and r_new <= r_old):
            best = rec
    return best or {}


class TelemetryCollector:
    """Merge per-process telemetry/trace streams into one aligned list.

    ``paths`` are stream *base* paths (generations are discovered); use
    :meth:`from_dir` to sweep a trace directory (``*.jsonl``, skipping
    numbered generation files — they are folded into their base)."""

    def __init__(self, paths: Iterable[str] = ()):
        self.paths: list[str] = []
        for p in paths:
            self.add(p)

    @classmethod
    def from_dir(cls, directory: str) -> "TelemetryCollector":
        coll = cls()
        base_paths = set(_glob.glob(os.path.join(directory, "*.jsonl")))
        # A stream whose live file was rotated away (or never re-created
        # before the process died) exists only as `<base>.jsonl.N`; the
        # collector is keyed by base path — `generations()` finds the
        # .N files — so discover bases from rotated names too.
        for p in _glob.glob(os.path.join(directory, "*.jsonl.*")):
            base, _, n = p.rpartition(".")
            if n.isdigit():
                base_paths.add(base)
        for p in sorted(base_paths):
            coll.add(p)
        return coll

    def add(self, path: str) -> None:
        if path not in self.paths:
            self.paths.append(path)

    def records(self) -> list[dict]:
        """The merged timeline: every stream's records, clock-aligned,
        identity-stamped, span-deduped, sorted by aligned timestamp."""
        merged: list[dict] = []
        seen_spans: set = set()
        for path in self.paths:
            recs: list[dict] = []
            for gen in generations(path):
                recs.extend(read_jsonl(gen))
            info = _best_info(recs)
            off = float(info.get("clock_offset_s") or 0.0)
            stamp = {k: info[k] for k in ("host", "pid", "role")
                     if k in info}
            for rec in recs:
                if rec.get("kind") == SPAN_KIND:
                    key = (rec.get("trace"), rec.get("span"))
                    if key in seen_spans:
                        continue
                    seen_spans.add(key)
                rec = dict(rec)
                for k, v in stamp.items():
                    rec.setdefault(k, v)
                if off:
                    for k in ("ts", "t0"):
                        if isinstance(rec.get(k), (int, float)):
                            rec[k] = rec[k] + off
                rec["stream"] = os.path.basename(path)
                merged.append(rec)
        merged.sort(key=_sort_ts)
        return merged

    def write(self, path_or_file) -> int:
        """Dump the merged timeline as JSONL; returns the record count."""
        import json

        recs = self.records()

        def _write(f) -> None:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")

        if isinstance(path_or_file, str):
            with open(path_or_file, "w", encoding="utf-8") as f:
                _write(f)
        else:
            _write(path_or_file)
        return len(recs)


def _sort_ts(rec: dict) -> float:
    ts = rec.get("ts")
    if isinstance(ts, (int, float)):
        return float(ts)
    t0 = rec.get("t0")
    if isinstance(t0, (int, float)):
        return float(t0)
    return 0.0
