"""Trace-context propagation: ids, ambient scopes, and the span stream.

One *trace* is the life of one logical operation across processes — a
commit from worker encode through wire, shard fold, journal fsync, and
standby replication; a served request from client submit through the
micro-batcher's dispatch. Every timed segment is a *span*: a record

    {"kind": "trace_span", "trace": ..., "span": ..., "parent": ...,
     "name": ..., "t0": <wall-clock start>, "dur": <seconds>, ...}

emitted into the process's telemetry event stream (so ``write_jsonl``
exports it) and — when a trace directory resolves — appended immediately
to a per-process ``trace-<role>-<pid>.jsonl`` so a SIGKILL'd process
loses at most one torn line (the collector tolerates that tail with the
same rule as ``read_jsonl``). The context travels:

* **within a thread** ambiently (thread-local), so nested scopes become
  parent/child spans without threading arguments through call sites;
* **across thread pools** explicitly via :func:`adopt` (pool threads do
  not inherit thread-locals — the sharded fan-out captures the context
  and re-establishes it inside each stripe closure);
* **across processes** as two JSON header fields (``trace``/``parent``)
  on netps/serving wire frames, gated behind ``CAPS["tracing"]`` — a
  peer that never advertised the bit is sent zero new bytes.

Everything here is stdlib + the env registry: no jax, no numpy — the
same contract as the telemetry core. With ``DKTPU_TRACE`` unset (the
default) every entry point is a cheap no-op.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from contextlib import contextmanager
from typing import NamedTuple, Optional

from distkeras_tpu.runtime import config

#: event kind of one span record (rides the telemetry event stream and
#: the per-process trace stream alike; the collector dedups on ids).
SPAN_KIND = "trace_span"
#: event kind of the per-process identity record every stream carries:
#: host, pid, role, boot_id, and the current clock-offset estimate.
PROCESS_INFO_KIND = "process_info"

_TLS = threading.local()
_STATE_LOCK = threading.Lock()
#: explicit role override (set_role); the env var is the fallback.
_ROLE: list = [""]
_BOOT_ID: list = [None]
#: lazily opened per-process span stream: {"f", "path", "pid"}.
_WRITER: dict = {"f": None, "path": None, "pid": None}


def enabled() -> bool:
    """Whether tracing is on (``DKTPU_TRACE``); read live so tests and
    late launchers can flip it without re-importing."""
    return config.env_bool("DKTPU_TRACE")


class TraceContext(NamedTuple):
    """The two ids that travel: the trace and the current span within it."""

    trace: str
    span: str


def new_id() -> str:
    """One 16-hex-char id (half a uuid4 — ample for per-run uniqueness)."""
    return uuid.uuid4().hex[:16]


def current() -> Optional[TraceContext]:
    """This thread's ambient trace context (None outside any scope)."""
    return getattr(_TLS, "ctx", None)


def set_role(role: str) -> None:
    """Stamp this process's role label (``ps``/``standby``/``shard0``/
    ``worker1``/...). An explicit ``DKTPU_TRACE_ROLE`` wins — the operator
    labeled the process on purpose; launchers calling in here are only
    providing the default."""
    with _STATE_LOCK:
        _ROLE[0] = str(role)


def role() -> str:
    """This process's role label: the env var, else :func:`set_role`'s
    value, else ``proc``."""
    env = config.env_str("DKTPU_TRACE_ROLE")
    if env:
        return env
    return _ROLE[0] or "proc"


def boot_id() -> str:
    """The kernel boot id (same source as the shm same-host check), or a
    per-process fallback uuid where /proc is absent."""
    if _BOOT_ID[0] is None:
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                _BOOT_ID[0] = f.read().strip()
        except OSError:
            _BOOT_ID[0] = uuid.uuid4().hex
    return _BOOT_ID[0]


def trace_dir() -> str:
    """Where this process streams spans + flight dumps: ``DKTPU_TRACE_DIR``,
    falling back to the PS state dir (the chaos drills already point one at
    scratch space); empty = no streaming (events/ring only)."""
    d = config.env_str("DKTPU_TRACE_DIR")
    if d:
        return d
    return config.env_str("DKTPU_PS_STATE_DIR")


def process_info_record() -> dict:
    """The stream-identity record: who wrote this file, on which clock."""
    from distkeras_tpu.telemetry.tracing import clock

    return {"kind": PROCESS_INFO_KIND, "ts": time.time(),
            "host": socket.gethostname(), "pid": os.getpid(),
            "role": role(), "boot_id": boot_id(),
            "clock_offset_s": clock.offset(), "clock_rtt_s": clock.rtt()}


# -- the per-process span stream -------------------------------------------

def _rotate_bytes() -> int:
    mb = config.env_float("DKTPU_TELEMETRY_ROTATE_MB") or 0.0
    return int(mb * (1 << 20))


def _stream_write(rec: dict) -> None:
    """Append one record to the per-process trace stream (best-effort:
    tracing must never take the data plane down). Rotation mirrors the
    exporter rule: at/over ``DKTPU_TELEMETRY_ROTATE_MB`` the live file is
    renamed to the next ``<path>.<n>`` generation before the append."""
    d = trace_dir()
    if not d:
        return
    line = json.dumps(rec)
    with _STATE_LOCK:
        f = _WRITER["f"]
        if f is None or _WRITER["pid"] != os.getpid():
            # Fresh open (first span, or a fork inherited the parent's
            # handle — each pid owns its own stream file).
            try:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"trace-{role()}-{os.getpid()}.jsonl")
                f = open(path, "a", encoding="utf-8")
            except OSError:
                return
            _WRITER.update(f=f, path=path, pid=os.getpid())
            f.write(json.dumps(process_info_record()) + "\n")
        try:
            limit = _rotate_bytes()
            if limit and f.tell() >= limit:
                f.close()
                _rotate_generations(_WRITER["path"])
                f = open(_WRITER["path"], "a", encoding="utf-8")
                _WRITER["f"] = f
                f.write(json.dumps(process_info_record()) + "\n")
            f.write(line + "\n")
            f.flush()
        except (OSError, ValueError):
            _WRITER.update(f=None, path=None, pid=None)


def _rotate_generations(path: str) -> None:
    """Atomic-rename rotation: the live file becomes the next numbered
    generation (``<path>.1`` is the oldest); the collector reads
    generations in numeric order, then the live file."""
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    os.replace(path, f"{path}.{n}")


def refresh_process_info() -> None:
    """Re-stamp the stream with a fresh identity record (the clock module
    calls in when its offset estimate improves, so the collector can use
    the best estimate the process ever had)."""
    if _WRITER["f"] is not None and _WRITER["pid"] == os.getpid():
        _stream_write(process_info_record())


def stream_path() -> Optional[str]:
    """The live trace-stream path, once anything has been written."""
    return _WRITER["path"] if _WRITER["pid"] == os.getpid() else None


def _reset_stream() -> None:
    """Tests only: drop the open stream so the next span re-resolves the
    directory/role."""
    with _STATE_LOCK:
        f = _WRITER["f"]
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        _WRITER.update(f=None, path=None, pid=None)


# -- span emission ----------------------------------------------------------

def record_span(name: str, trace: str, span: str, parent: Optional[str],
                t0: float, dur: float, **fields) -> None:
    """Emit one finished span: into the telemetry event stream (exported
    by ``write_jsonl``; the core's event tap feeds the flight ring) and
    onto the per-process trace stream."""
    rec = {"name": name, "trace": trace, "span": span,
           "t0": round(t0, 6), "dur": round(dur, 6)}
    if parent:
        rec["parent"] = parent
    if fields:
        rec.update(fields)
    from distkeras_tpu import telemetry

    telemetry.event(SPAN_KIND, rec)
    _stream_write(dict(rec, kind=SPAN_KIND, ts=rec["t0"]))


def emit(name: str, ctx: Optional[TraceContext], t0: float, dur: float,
         **fields) -> None:
    """Record one already-timed span as a child of ``ctx`` (the server
    side's lock-wait measurement, where a context manager cannot wrap the
    acquire). No-op without a context or with tracing off."""
    if ctx is None or not enabled():
        return
    record_span(name, ctx.trace, new_id(), ctx.span, t0, dur, **fields)


@contextmanager
def trace_scope(name: str, **fields):
    """Timed span scope: joins the ambient trace as a child span, or ROOTS
    a new trace when no context is ambient (the client's ``commit`` root).
    Yields the scope's :class:`TraceContext` (None when tracing is off)."""
    if not enabled():
        yield None
        return
    prev = getattr(_TLS, "ctx", None)
    trace = prev.trace if prev is not None else new_id()
    parent = prev.span if prev is not None else None
    ctx = TraceContext(trace, new_id())
    _TLS.ctx = ctx
    t0 = time.time()
    p0 = time.perf_counter()
    try:
        yield ctx
    finally:
        dur = time.perf_counter() - p0
        _TLS.ctx = prev
        record_span(name, trace, ctx.span, parent, t0, dur, **fields)


@contextmanager
def child_scope(name: str, **fields):
    """Like :func:`trace_scope` but records ONLY inside an existing trace
    — a segment with no ambient context is a no-op, never an orphan root
    (the server's fold/fsync segments use this: an untraced commit must
    not mint trace ids)."""
    if not enabled() or getattr(_TLS, "ctx", None) is None:
        yield None
        return
    with trace_scope(name, **fields) as ctx:
        yield ctx


@contextmanager
def adopt(ctx: Optional[TraceContext]):
    """Establish ``ctx`` as this thread's ambient context without emitting
    a span — how the context crosses thread pools (stripe fan-out, the
    overlap lanes) and how a server adopts a request header's context."""
    if ctx is None or not enabled():
        yield None
        return
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = prev


# -- wire-header helpers ----------------------------------------------------

def wire_fields() -> dict:
    """The two header fields an outgoing traced request carries (``{}``
    with tracing off or outside any scope — an absent JSON key is an
    absent wire byte, which is the whole capability-gating story)."""
    ctx = getattr(_TLS, "ctx", None)
    if ctx is None or not enabled():
        return {}
    return {"trace": ctx.trace, "parent": ctx.span}


def header_ctx(header: dict) -> Optional[TraceContext]:
    """The context an incoming request header carries (None untraced).
    The carried ``parent`` is the CLIENT's span — server-side segments
    recorded under this context become its children."""
    trace = header.get("trace")
    if not trace or not enabled():
        return None
    return TraceContext(str(trace), str(header.get("parent") or ""))
