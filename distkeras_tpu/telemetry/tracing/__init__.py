"""Fleet-wide distributed tracing: context, clocks, flight ring, collector.

The observability layer ISSUE 14 adds on top of the per-process telemetry
core: one commit (or one served request) becomes one *trace* whose spans
span processes — worker encode, wire, queue-behind-fold, fold, fsync,
standby replication — stitched by ``(trace, parent)`` ids carried in wire
headers behind ``CAPS["tracing"]`` and aligned onto one clock by the
NTP-style exchange piggybacked on join/heartbeat. See
docs/OBSERVABILITY.md ("Distributed tracing") for the model; render the
analysis with ``python -m distkeras_tpu.telemetry report --trace <dir>``.

Everything is gated on ``DKTPU_TRACE`` (default off: no ids, no extra
wire bytes, no span records) and stays stdlib-only — importable wherever
the telemetry core is.
"""

from __future__ import annotations

from distkeras_tpu.telemetry.tracing import clock
from distkeras_tpu.telemetry.tracing.analysis import (render_trace_report,
                                                      trace_report)
from distkeras_tpu.telemetry.tracing.collector import (TelemetryCollector,
                                                       generations)
from distkeras_tpu.telemetry.tracing.context import (
    PROCESS_INFO_KIND,
    SPAN_KIND,
    TraceContext,
    adopt,
    boot_id,
    child_scope,
    current,
    emit,
    enabled,
    header_ctx,
    new_id,
    process_info_record,
    record_span,
    role,
    set_role,
    trace_dir,
    trace_scope,
    wire_fields,
)
from distkeras_tpu.telemetry.tracing.recorder import (
    FlightRecorder,
    flight_dump,
    get_ring,
    install_crash_hooks,
    ring_head,
)

__all__ = [
    "SPAN_KIND", "PROCESS_INFO_KIND", "TraceContext",
    "enabled", "current", "new_id", "trace_scope", "child_scope", "adopt",
    "emit", "record_span", "wire_fields", "header_ctx",
    "role", "set_role", "boot_id", "trace_dir", "process_info_record",
    "FlightRecorder", "get_ring", "ring_head", "flight_dump",
    "install_crash_hooks",
    "TelemetryCollector", "generations",
    "trace_report", "render_trace_report",
    "clock",
]

# The flight ring is fed through the telemetry core's event tap: every
# event (trace spans included — they ride the event stream) lands in the
# ring when tracing is on, with no second call site in instrumented code.
from distkeras_tpu.telemetry import core as _core  # noqa: E402
from distkeras_tpu.telemetry.tracing import recorder as _recorder  # noqa: E402

_core.set_event_tap(_recorder._tap)
