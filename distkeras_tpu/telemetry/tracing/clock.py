"""NTP-style clock-offset estimation over the existing RPC round trips.

Cross-process trace alignment needs every stream on one clock. Rather
than a daemon, the estimate piggybacks on frames already flying: a traced
client stamps ``ct0`` (its wall clock) onto join/heartbeat requests; the
server echoes it back with ``st1`` (request receive) and ``st2`` (reply
build) — the four-timestamp exchange::

    offset = ((st1 - ct0) + (st2 - ct3)) / 2        # server - client
    rtt    = (ct3 - ct0) - (st2 - st1)

where ``ct3`` is the client's receive time. The estimate with the
SMALLEST observed rtt wins (asymmetric queuing corrupts high-rtt
samples; the min-rtt sample bounds the error by rtt/2). Each improvement
re-stamps the process's trace stream with a fresh ``process_info``
record, so the collector aligns with the best estimate the process ever
had. The fields ride only on requests that already carried ``ct0``, so a
peer without ``CAPS["tracing"]`` sees zero new bytes in either direction.

The offset is *this process -> its (primary) server peer*; server
processes never stamp ``ct0`` and keep offset 0.0 — the PS is the fleet's
reference clock, which is exactly what the commit critical path needs
(every segment either happens on the PS or is measured against it).
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()
#: best estimate so far: offset (server - client, seconds) at min rtt.
_EST: dict = {"offset": 0.0, "rtt": None}


def observe(ct0: float, st1: float, st2: float, ct3: float) -> None:
    """Fold one four-timestamp exchange into the estimate."""
    rtt = (ct3 - ct0) - (st2 - st1)
    offset = ((st1 - ct0) + (st2 - ct3)) / 2.0
    improved = False
    with _LOCK:
        best = _EST["rtt"]
        if best is None or rtt < best:
            _EST["offset"] = offset
            _EST["rtt"] = max(rtt, 0.0)
            improved = True
    if improved:
        from distkeras_tpu.telemetry.tracing import context

        context.refresh_process_info()


def observe_reply(ct0: float, reply: dict, ct3: float) -> None:
    """Client convenience: feed a reply header's ``st1``/``st2`` echo (a
    no-op when the server did not answer the exchange)."""
    st1, st2 = reply.get("st1"), reply.get("st2")
    if st1 is None or st2 is None:
        return
    try:
        observe(float(ct0), float(st1), float(st2), float(ct3))
    except (TypeError, ValueError):
        return


def offset() -> float:
    """Best current offset estimate (seconds to ADD to this process's
    wall-clock timestamps to land on the reference clock)."""
    return _EST["offset"]


def rtt():
    """The rtt of the winning sample (None = no exchange yet)."""
    return _EST["rtt"]


def reset() -> None:
    """Tests only."""
    with _LOCK:
        _EST["offset"] = 0.0
        _EST["rtt"] = None
