"""Telemetry exporters: append-only JSONL and Prometheus text format.

JSONL records are shape-compatible with ``MetricsLogger``'s per-round records
(one JSON object per line); telemetry adds records carrying a ``kind`` field
(``telemetry_summary``, plus any :meth:`Telemetry.event` records), so one
``run.jsonl`` can hold the round stream and the aggregate dump together and
``python -m distkeras_tpu.telemetry report`` renders both.

The Prometheus dump is the text exposition format (histograms as cumulative
``le`` buckets) for scraping or one-shot file drops; :func:`parse_prometheus`
is the matching reader used by the round-trip tests.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional, TextIO, Union

from distkeras_tpu.runtime import config
from distkeras_tpu.telemetry.core import BUCKET_BOUNDS, Telemetry

SUMMARY_KIND = "telemetry_summary"


def rotate_jsonl(path: str) -> Optional[str]:
    """Size-bounded JSONL rotation (``DKTPU_TELEMETRY_ROTATE_MB``): a file
    at/over the bound is atomically renamed to the next ``<path>.<n>``
    generation (numbered from 1, oldest first) so the next append starts a
    fresh live file; the collector reads generations in order. Returns the
    generation path, or None when no rotation was due (0 = disabled)."""
    mb = config.env_float("DKTPU_TELEMETRY_ROTATE_MB") or 0.0
    limit = int(mb * (1 << 20))
    if not limit:
        return None
    try:
        if not os.path.exists(path) or os.path.getsize(path) < limit:
            return None
        n = 1
        while os.path.exists(f"{path}.{n}"):
            n += 1
        os.replace(path, f"{path}.{n}")
        return f"{path}.{n}"
    except OSError:
        return None


def write_jsonl(tele: Telemetry, path_or_file: Union[str, TextIO],
                extra: Optional[dict] = None,
                since: Optional[dict] = None) -> None:
    """Append every recorded event plus one aggregate-summary record.

    ``since`` (a :meth:`Telemetry.mark`) windows the dump to activity after
    the mark — how per-run clients (MetricsLogger) share the process-global
    registry without re-attributing a previous run's work. Each dump leads
    with one ``process_info`` identity record (host/pid/role/boot_id +
    clock-offset estimate) so the cross-process collector can attribute
    and align the stream; path dumps rotate first when
    ``DKTPU_TELEMETRY_ROTATE_MB`` says the file is due."""
    if since is not None:
        summary, events = tele.delta(since)
    else:
        summary, events = tele.snapshot(), tele.events()

    def _write(f: TextIO) -> None:
        from distkeras_tpu.telemetry.tracing import process_info_record

        f.write(json.dumps(process_info_record()) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")
        rec = {"kind": SUMMARY_KIND, "ts": time.time(), **summary}
        if extra:
            rec.update(extra)
        f.write(json.dumps(rec) + "\n")

    if isinstance(path_or_file, str):
        rotate_jsonl(path_or_file)
        with open(path_or_file, "a") as f:
            _write(f)
    else:
        _write(path_or_file)


def read_jsonl(path: str, strict: bool = False) -> list[dict]:
    """All records of a telemetry/metrics JSONL.

    Crash-tolerant by design: a process killed mid-append leaves a
    truncated final line, and post-mortem ``telemetry report`` matters most
    on exactly those runs — a torn *final* line is always skipped silently,
    never an error. Malformed *interior* lines are skipped with a warning
    (they indicate concurrent-writer damage, not a crash); ``strict=True``
    raises on them instead, still tolerating the torn tail."""
    records: list[dict] = []
    bad: list[int] = []
    # Streaming with a one-line hold-back (these files are exactly the ones
    # that grow for hours — never slurp them): a malformed line's verdict is
    # deferred until we know whether anything follows it. Followed by more
    # content -> interior damage; at EOF -> the torn tail.
    pending_bad = 0
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            if pending_bad:
                if strict:
                    raise ValueError(
                        f"malformed JSONL record at {path}:{pending_bad}")
                bad.append(pending_bad)
                pending_bad = 0
            line = raw.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                pending_bad = i
    if bad:
        import warnings

        warnings.warn(
            f"{path}: skipped {len(bad)} malformed interior JSONL line(s) "
            f"(first at line {bad[0]})", stacklevel=2)
    return records


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def prometheus_text(tele: Telemetry) -> str:
    """The registry in Prometheus text exposition format.

    Spans/histograms become one ``dktpu_span_seconds`` histogram family with
    a ``span`` label; counters and gauges become ``dktpu_counter_total`` /
    ``dktpu_gauge`` families with a ``name`` label — fixed families keep the
    dump schema-stable as instrumentation points are added.
    """
    snap = tele.snapshot()
    out = []
    out.append("# TYPE dktpu_counter_total counter")
    for name, value in sorted(snap["counters"].items()):
        out.append(f'dktpu_counter_total{{name="{_sanitize(name)}"}} {value}')
    out.append("# TYPE dktpu_gauge gauge")
    for name, g in sorted(snap["gauges"].items()):
        out.append(f'dktpu_gauge{{name="{_sanitize(name)}"}} '
                   f'{g.get("value", 0.0)}')
    out.append("# TYPE dktpu_span_seconds histogram")
    for name, h in sorted(snap["spans"].items()):
        label = _sanitize(name)
        cum = 0
        for bound, c in zip(BUCKET_BOUNDS, h.get("buckets", [])):
            cum += c
            out.append(
                f'dktpu_span_seconds_bucket{{span="{label}",le="{bound!r}"}} '
                f"{cum}")
        out.append(
            f'dktpu_span_seconds_bucket{{span="{label}",le="+Inf"}} '
            f'{h.get("count", 0)}')
        out.append(f'dktpu_span_seconds_sum{{span="{label}"}} '
                   f'{h.get("total", 0.0)}')
        out.append(f'dktpu_span_seconds_count{{span="{label}"}} '
                   f'{h.get("count", 0)}')
    return "\n".join(out) + "\n"


_PROM_LINE = re.compile(
    r'^(?P<metric>[a-zA-Z0-9_]+)(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$')


def parse_prometheus(text: str) -> dict:
    """Parse :func:`prometheus_text` output back into
    ``{metric: {label_tuple: value}}`` (the round-trip test's reader)."""
    parsed: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        labels = tuple(
            tuple(kv.split("=", 1)) for kv in
            (m.group("labels") or "").split(",") if "=" in kv)
        labels = tuple((k, v.strip('"')) for k, v in labels)
        parsed.setdefault(m.group("metric"), {})[labels] = float(
            m.group("value"))
    return parsed
