"""Utility parity layer — the reference's ``distkeras/utils.py`` surface.

Functions keep their reference names where behavior maps 1:1 so ported notebooks can
do ``from distkeras_tpu.utils import ...`` and run.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataframe import DataFrame
from distkeras_tpu.models.base import Model, uniform_weights  # noqa: F401 (re-export)
from distkeras_tpu.runtime.serialization import (
    deserialize_model,
    serialize_model,
)


def serialize_keras_model(model: Model) -> bytes:
    """Reference ``utils.serialize_keras_model``: model -> portable bytes."""
    return serialize_model(model)


def deserialize_keras_model(data: bytes) -> Model:
    """Reference ``utils.deserialize_keras_model``: bytes -> model."""
    return deserialize_model(data)


def shuffle(dataframe: DataFrame, seed: int = 0) -> DataFrame:
    """Reference ``utils.shuffle(dataframe)``: random row permutation."""
    return dataframe.shuffle(seed=seed)


def precache(dataframe: DataFrame) -> DataFrame:
    """Reference ``utils.precache``: force materialization (no-op here — numpy
    columns are always materialized)."""
    return dataframe.precache()


def new_dataframe_row(row: dict, name: str, value) -> dict:
    """Reference ``utils.new_dataframe_row``: row dict + one new column value."""
    out = dict(row)
    out[name] = value
    return out


def to_dense_vector(value, length: int) -> np.ndarray:
    """Reference ``utils.to_dense_vector``-style helper: one-hot of ``value``."""
    v = np.zeros((length,), np.float32)
    v[int(value)] = 1.0
    return v
