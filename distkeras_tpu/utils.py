"""Utility parity layer — the reference's ``distkeras/utils.py`` surface.

Functions keep their reference names where behavior maps 1:1 so ported notebooks can
do ``from distkeras_tpu.utils import ...`` and run.
"""

from __future__ import annotations

import numpy as np

from distkeras_tpu.data.dataframe import DataFrame
from distkeras_tpu.models.base import Model, uniform_weights  # noqa: F401 (re-export)
from distkeras_tpu.runtime.serialization import (
    deserialize_model,
    serialize_model,
)


def set_keras_base_directory(path: str = ".") -> None:
    """Reference parity (``distkeras/utils.py -> set_keras_base_directory``):
    pointed 2016-era Keras at a writable ``~/.keras`` on Spark executors. No
    TPU equivalent is needed — models are pure pytrees, nothing touches a
    Keras home directory — but ported notebooks may still call it, so it
    accepts the call and points Keras-3's home at ``<path>/.keras``."""
    import os.path

    from distkeras_tpu.runtime import config

    config.env_set("KERAS_HOME", os.path.join(path, ".keras"))


def serialize_keras_model(model: Model) -> bytes:
    """Reference ``utils.serialize_keras_model``: model -> portable bytes."""
    return serialize_model(model)


def deserialize_keras_model(data: bytes) -> Model:
    """Reference ``utils.deserialize_keras_model``: bytes -> model."""
    return deserialize_model(data)


def shuffle(dataframe: DataFrame, seed: int = 0) -> DataFrame:
    """Reference ``utils.shuffle(dataframe)``: random row permutation."""
    return dataframe.shuffle(seed=seed)


def precache(dataframe: DataFrame) -> DataFrame:
    """Reference ``utils.precache``: force materialization (no-op here — numpy
    columns are always materialized)."""
    return dataframe.precache()


def new_dataframe_row(row: dict, name: str, value) -> dict:
    """Reference ``utils.new_dataframe_row``: row dict + one new column value."""
    out = dict(row)
    out[name] = value
    return out


def to_dense_vector(value, length: int) -> np.ndarray:
    """Reference ``utils.to_dense_vector``-style helper: one-hot of ``value``."""
    v = np.zeros((length,), np.float32)
    v[int(value)] = 1.0
    return v
