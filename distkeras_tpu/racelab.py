"""A genuinely-raced parameter server, for validating the async mapping.

The framework maps the reference's asynchronous disciplines onto deterministic
window-K collective folds (``parallel/disciplines.py``). That mapping's claim —
"same aggregate semantics as the raced socket server" (SURVEY.md §7 hard part
(a): ADAG-equivalent accuracy) — deserves evidence, not assertion. This module
re-creates the reference's actual architecture on host threads:

* a **parameter-server object guarding the center variable with a plain lock**
  (the reference's ``SocketParameterServer.handle_commit`` — SURVEY.md §3.4:
  one handler thread per worker, ``with lock: fold(delta)``);
* **N worker threads** that each loop ``pull -> K local steps -> commit``
  with NO barriers — commits land in whatever order the OS schedules, and
  staleness is real (DynSGD's counter semantics: server update-counter minus
  the worker's pull-time counter), not simulated.

Gradient compute is jitted JAX on CPU (releases the GIL, so threads truly
interleave); the server folds in numpy under the lock, exactly the
reference's data path minus the socket serialization.

``tests/test_raced_ps.py`` trains the same model on the same data through
this raced server AND through the deterministic engines, across seeds, and
asserts final-accuracy parity — closing the async-mapping argument with a
measurement.
"""

from __future__ import annotations

import threading
from typing import Callable, Sequence

import numpy as np

from distkeras_tpu.netps.errors import ServerClosedError
from distkeras_tpu.netps.fold import check_discipline, fold_delta


class RacedParameterServer:
    """The reference's server half: lock + fold, commit-order = thread race.

    ``discipline``: 'downpour' (center += delta), 'adag' (center += delta/K,
    the worker pre-normalizes), 'dynsgd' (center += delta/(staleness+1)), or
    'aeasgd'/'eamsgd' (center += elastic difference — the reference routed
    both elastic trainers through the plain ``DeltaParameterServer``; all
    elasticity lives on the worker side, SURVEY.md §3.3).

    The fold itself is :func:`distkeras_tpu.netps.fold.fold_delta` — the
    SAME function the networked ``PSServer`` applies, so the raced-parity
    measurements in ``tests/test_raced_ps.py`` cover both transports.
    """

    def __init__(self, center: Sequence[np.ndarray], discipline: str = "adag"):
        check_discipline(discipline)
        self._lock = threading.Lock()
        self._center = [np.array(a, np.float32) for a in center]
        self._updates = 0  # server update counter (DynSGD staleness basis)
        self._closed = False
        self.discipline = discipline
        #: realized staleness of each commit, in commit order (recorded for
        #: EVERY discipline — the race-happened evidence; only dynsgd also
        #: *scales* by it).
        self.commit_log: list[int] = []

    def close(self) -> None:
        """Shut the server: every subsequent ``pull``/``commit`` raises a
        typed :class:`ServerClosedError`, so a leaked worker thread exits
        its loop instead of committing into a dead center forever."""
        with self._lock:
            self._closed = True

    def _check_open(self) -> None:
        if self._closed:
            raise ServerClosedError("RacedParameterServer is closed")

    def pull(self) -> tuple[list[np.ndarray], int]:
        with self._lock:
            self._check_open()
            return [a.copy() for a in self._center], self._updates

    def commit(self, delta: Sequence[np.ndarray], pulled_counter: int) -> None:
        with self._lock:
            self._check_open()
            staleness = self._updates - pulled_counter
            self.commit_log.append(staleness)
            fold_delta(self._center, delta, self.discipline, staleness)
            self._updates += 1

    def center(self) -> list[np.ndarray]:
        with self._lock:
            return [a.copy() for a in self._center]


def run_raced(
    *,
    center: Sequence[np.ndarray],
    local_steps: Callable,
    worker_batches: Sequence[Sequence],
    window: int,
    discipline: str = "adag",
    overlap_first_round: bool = False,
    alpha: float = 0.05,
) -> tuple[list[np.ndarray], RacedParameterServer]:
    """Race ``len(worker_batches)`` threads against one server.

    ``local_steps(params_list, batch) -> params_list`` runs the K-step local
    window (jitted JAX; must be thread-safe, which jitted functions are).
    For 'eamsgd' the callable may carry per-worker auxiliary state (momentum
    velocities): ``local_steps(params_list, batch, aux) -> (params_list,
    aux)`` with ``aux=None`` on the first round. ``worker_batches[w]`` is
    worker w's sequence of per-round batches — its Spark-partition analogue;
    one commit per batch.

    Elastic disciplines ('aeasgd'/'eamsgd') run the reference's §3.3 worker
    loop: the local replica PERSISTS across rounds (exploration is the
    point); each round the worker pulls the center, runs K local steps from
    its own replica, computes ``e = alpha*(w_local − center_pulled)``,
    moves itself ``w_local −= e``, and commits ``e`` (server: center += e).
    Because the pull and the commit bracket the K-step window with no lock
    held, other workers' elastic terms land in between — the commit is
    computed against a genuinely stale center, which is exactly the raced
    interleaving the window-K fold serializes.

    ``overlap_first_round`` holds every worker at a barrier after its first
    pull, guaranteeing the first W commits race (staleness 0..W-1 realized
    deterministically) even on hosts whose scheduler would otherwise
    serialize the threads. Later rounds race freely either way.

    Returns the final center and the server (whose ``commit_log`` shows the
    realized staleness distribution).
    """
    ps = RacedParameterServer(center, discipline)
    errors: list[BaseException] = []
    elastic = discipline in ("aeasgd", "eamsgd")
    stateful = discipline == "eamsgd"
    gate = (threading.Barrier(len(worker_batches))
            if overlap_first_round else None)

    def work(w: int) -> None:
        try:
            local = [np.array(a, np.float32) for a in center] if elastic else None
            aux = None
            for r, batch in enumerate(worker_batches[w]):
                pulled, counter = ps.pull()
                if gate is not None and r == 0:
                    gate.wait()
                start = local if elastic else pulled
                if stateful:
                    new, aux = local_steps(start, batch, aux)
                else:
                    new = local_steps(start, batch)
                if elastic:
                    e = [alpha * (np.asarray(n, np.float32) - p)
                         for n, p in zip(new, pulled)]
                    local = [np.asarray(n, np.float32) - d
                             for n, d in zip(new, e)]
                    ps.commit(e, counter)
                else:
                    delta = [np.asarray(n, np.float32) - p
                             for n, p in zip(new, pulled)]
                    if discipline == "adag":
                        delta = [d / float(window) for d in delta]
                    ps.commit(delta, counter)
        except BaseException as e:  # noqa: BLE001 - surface on the main thread
            errors.append(e)

    threads = [threading.Thread(target=work, args=(w,))
               for w in range(len(worker_batches))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return ps.center(), ps
