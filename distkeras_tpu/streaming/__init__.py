"""Streaming continual training: the online-learning loop, closed.

The reference's ``Trainer.train(dataframe)`` is batch-shaped; production
traffic is a stream. This package is the connective tissue between the
pieces the repo already has — the netps parameter server, the elastic
claim queue, checkpoint/restore, the serving ``ModelRegistry``'s hot
swap, and the health plane's drift sentinels — turned into one loop::

    source -> RoundFeeder staging -> claim queue -> train -> commit(PS)
       ^                                              |
       |            OffsetJournal (durable)  <--------+
       |                                              v
    resume at last committed offset     checkpoint -> hot-swap -> serve

* :mod:`~distkeras_tpu.streaming.source` — the :class:`StreamSource`
  contract (file tail + socket feed) with fault injection
  (``feed_gap@R:S``, ``drift@R``) and a record codec.
* :mod:`~distkeras_tpu.streaming.journal` — the durable
  :class:`OffsetJournal`: the exactly-once ingest argument lives there.
* :mod:`~distkeras_tpu.streaming.items` — :class:`WorkQueue`, the claim
  queue generalized to open-ended item streams (ElasticTraining's fixed
  ``rounds x W`` schedule is the bounded special case).
* :mod:`~distkeras_tpu.streaming.evaluate` — windowed online eval +
  :class:`DriftWatch` (loss-divergence pages via ``AlertManager``,
  checkpoint-on-drift, recovery timing).
* :mod:`~distkeras_tpu.streaming.runtime` — :class:`StreamingTraining`,
  the fleet-schedulable runtime tying it together, and
  :class:`StreamingSession`, the Supervisor-compatible wrapper.

docs/STREAMING.md is the narrative: source contract, the offset-journal
exactly-once argument, the drift -> page -> checkpoint -> rollback
lifecycle, and the failure matrix.
"""

from distkeras_tpu.streaming.evaluate import DriftWatch, WindowedEval
from distkeras_tpu.streaming.items import WorkQueue
from distkeras_tpu.streaming.journal import OffsetJournal, replayed_offsets
from distkeras_tpu.streaming.runtime import StreamingSession, StreamingTraining
from distkeras_tpu.streaming.source import (
    FileTailSource,
    SocketSource,
    StreamFileWriter,
    StreamProducer,
    StreamRecord,
    decode_record,
    encode_record,
)

__all__ = [
    "DriftWatch",
    "FileTailSource",
    "OffsetJournal",
    "SocketSource",
    "StreamFileWriter",
    "StreamProducer",
    "StreamRecord",
    "StreamingSession",
    "StreamingTraining",
    "WindowedEval",
    "WorkQueue",
    "decode_record",
    "encode_record",
    "replayed_offsets",
]
