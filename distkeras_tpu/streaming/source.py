"""Stream sources: where unbounded training data comes from.

The contract is one method::

    source.read(start_index=0, skip=frozenset()) -> Iterator[StreamRecord]

yielding records in **absolute stream order** (``record.index`` is the
record's ordinal in the whole stream, stable across restarts — it IS the
offset the :class:`~distkeras_tpu.streaming.journal.OffsetJournal`
journals). ``start_index``/``skip`` implement resume: deliver nothing
below the frontier, skip out-of-order-committed offsets. ``read`` may
block indefinitely waiting for the feed; consumers run it through the
RoundFeeder, whose stall watchdog turns a dried-up feed into
``FeederStalledError`` (the Supervisor path), not a silent hang.

Two transports:

* :class:`FileTailSource` — tails a growing frame file (a log of
  length-prefixed npz records, :class:`StreamFileWriter` the producer
  side). Polls for growth; a zero-length frame is end-of-stream.
* :class:`SocketSource` — a TCP feed from a :class:`StreamProducer`.
  The resume header carries ``start``; on a broken connection (source
  kill chaos) the client reconnects with the next undelivered index and
  keeps going, up to a reconnect budget.

Fault injection (the ambient compute :class:`FaultPlan`, indexes =
absolute record index): ``feed_gap@R:S`` holds record R back S seconds
before delivery — upstream of staging, so the gap propagates into the
consumer's stall accounting. ``drift@R`` starts a **distribution
shift**: from record R on, every label is rotated one class forward
(``(y + 1) % num_classes``) — a real concept shift the model must
relearn, visible as windowed-eval loss divergence. The one-shot trigger
is consumed at R but the shift is permanent for the life of the source;
runtimes persist the trigger index (journal ``meta``) so a post-kill
restart re-enters the drifted world.
"""

from __future__ import annotations

import io
import json
import socket
import struct
import threading
import time
from typing import Iterator, NamedTuple, Optional

import numpy as np

from distkeras_tpu.runtime import config

_LEN = struct.Struct(">I")


class StreamRecord(NamedTuple):
    """One training item off the wire: ``xs`` ``[K, B, ...]`` features,
    ``ys`` ``[K, B]`` labels (one worker-window, the claim-queue work
    unit), the producer-side event timestamp, the absolute stream index,
    and whether the injected drift transform touched it."""

    index: int
    xs: np.ndarray
    ys: np.ndarray
    ts: float
    drifted: bool = False


def encode_record(xs: np.ndarray, ys: np.ndarray, ts: float) -> bytes:
    """One framed record: 4-byte big-endian length + npz payload."""
    buf = io.BytesIO()
    np.savez(buf, xs=np.asarray(xs), ys=np.asarray(ys),
             ts=np.float64(ts))
    payload = buf.getvalue()
    return _LEN.pack(len(payload)) + payload


#: the end-of-stream frame: a zero payload length.
EOS_FRAME = _LEN.pack(0)


def decode_record(payload: bytes, index: int = -1) -> StreamRecord:
    with np.load(io.BytesIO(payload)) as z:
        return StreamRecord(index=index, xs=z["xs"], ys=z["ys"],
                            ts=float(z["ts"]))


class StreamFileWriter:
    """Producer side of :class:`FileTailSource`: append framed records to
    a file, flushed per record so a live tail sees them promptly."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")
        self.count = 0

    def append(self, xs, ys, ts: Optional[float] = None) -> int:
        self._f.write(encode_record(xs, ys,
                                    time.time() if ts is None else ts))
        self._f.flush()
        self.count += 1
        return self.count - 1

    def end(self) -> None:
        """Write the end-of-stream frame and close."""
        self._f.write(EOS_FRAME)
        self._f.flush()
        self._f.close()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class _SourceBase:
    """Shared fault-injection + bookkeeping for both transports."""

    def __init__(self, drift_classes: Optional[int] = None,
                 drift_from: Optional[int] = None):
        #: class count the drift rotation uses; None = infer per record
        #: from the label dtype's observed max (fine for test streams).
        self.drift_classes = drift_classes
        #: index the distribution shift began at (None = no drift yet).
        #: Pass the persisted value on resume — the fault one-shot was
        #: consumed before the kill, the drifted world was not.
        self.drift_from = drift_from
        self.delivered = 0
        self._stop = threading.Event()

    def close(self) -> None:
        self._stop.set()

    @property
    def closed(self) -> bool:
        return self._stop.is_set()

    def _apply_faults(self, rec: StreamRecord) -> StreamRecord:
        from distkeras_tpu import telemetry
        from distkeras_tpu.resilience import faults

        plan = faults.active_plan()
        if plan is not None:
            gap = plan.feed_gap(rec.index)
            if gap > 0:
                # The source goes silent: nothing reaches staging until the
                # gap passes (close() still wins promptly).
                self._stop.wait(gap)
            if plan.drift(rec.index):
                self.drift_from = rec.index
                telemetry.counter("stream.drift_injected").add(1)
                telemetry.event("stream_drift_injected", {"at": rec.index})
        if self.drift_from is not None and rec.index >= self.drift_from:
            ys = np.asarray(rec.ys)
            k = self.drift_classes or int(ys.max()) + 1
            rec = rec._replace(ys=(ys + 1) % max(k, 1), drifted=True)
        return rec

    def _deliver(self, rec: StreamRecord, skip) -> Optional[StreamRecord]:
        """Fault-transform + skip filter; None = journal already holds it."""
        rec = self._apply_faults(rec)
        if rec.index in skip:
            return None
        self.delivered += 1
        return rec


class FileTailSource(_SourceBase):
    """Tail a growing frame file; polls for growth every ``poll_s``
    (env ``DKTPU_STREAM_POLL_S``). A zero-length frame ends the stream;
    :meth:`close` aborts a tail blocked on a silent file."""

    def __init__(self, path: str, poll_s: Optional[float] = None, **kw):
        super().__init__(**kw)
        self.path = path
        self.poll_s = (config.env_float("DKTPU_STREAM_POLL_S")
                       if poll_s is None else float(poll_s))

    def _read_exact(self, f, n: int) -> Optional[bytes]:
        """n bytes from the current position, polling for file growth;
        None = source closed while waiting."""
        chunks: list[bytes] = []
        got = 0
        pos = f.tell()
        while got < n:
            chunk = f.read(n - got)
            if chunk:
                chunks.append(chunk)
                got += len(chunk)
                continue
            if self._stop.is_set():
                f.seek(pos)
                return None
            time.sleep(self.poll_s)
        return b"".join(chunks)

    def read(self, start_index: int = 0,
             skip: frozenset = frozenset()) -> Iterator[StreamRecord]:
        with open(self.path, "rb") as f:
            index = 0
            while not self._stop.is_set():
                head = self._read_exact(f, _LEN.size)
                if head is None:
                    return
                (size,) = _LEN.unpack(head)
                if size == 0:  # end-of-stream frame
                    return
                payload = self._read_exact(f, size)
                if payload is None:
                    return
                if index >= start_index:
                    rec = self._deliver(
                        decode_record(payload, index), skip)
                    if rec is not None:
                        yield rec
                index += 1


class StreamProducer:
    """A TCP record feed for :class:`SocketSource` — the test/bench
    producer. Keeps every appended record so any number of sequential
    connections can resume from any offset (the feed's durable upstream,
    playing the role a log broker would in production). ``kill`` drops
    live connections without EOS — the source-kill chaos drill."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._records: list[bytes] = []
        self._ended = False
        self._cv = threading.Condition()
        self._srv = socket.create_server((host, port))
        self.endpoint = "%s:%d" % self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._conns: list[socket.socket] = []
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="stream-producer", daemon=True)
        self._thread.start()

    def feed(self, xs, ys, ts: Optional[float] = None) -> int:
        with self._cv:
            self._records.append(
                encode_record(xs, ys, time.time() if ts is None else ts))
            self._cv.notify_all()
            return len(self._records) - 1

    def end(self) -> None:
        with self._cv:
            self._ended = True
            self._cv.notify_all()

    @property
    def count(self) -> int:
        with self._cv:
            return len(self._records)

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(10.0)
            header = b""
            while not header.endswith(b"\n"):
                chunk = conn.recv(1)
                if not chunk:
                    return
                header += chunk
            start = int(json.loads(header).get("start", 0))
            i = start
            while not self._stop.is_set():
                with self._cv:
                    while (i >= len(self._records) and not self._ended
                           and not self._stop.is_set()):
                        self._cv.wait(0.2)
                    if i < len(self._records):
                        frame = self._records[i]
                    elif self._ended:
                        conn.sendall(EOS_FRAME)
                        return
                    else:
                        continue
                conn.sendall(frame)
                i += 1
        except OSError:
            pass  # client gone (or killed connection): resume handles it
        finally:
            conn.close()

    def kill_connections(self) -> None:
        """Sever every live feed connection without EOS (source kill)."""
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._conns = []

    def close(self) -> None:
        self._stop.set()
        self.kill_connections()
        try:
            self._srv.close()
        except OSError:
            pass


class SocketSource(_SourceBase):
    """A TCP feed with reconnect-and-resume: the resume header tells the
    producer where to start, so a killed connection (or killed-and-
    restarted producer) costs retransmits, never records. Gives up after
    ``reconnect_s`` (env ``DKTPU_STREAM_RECONNECT_S``) of failed
    reconnects — then the iterator ends and the consumer's stall/stream
    accounting decides what that means."""

    def __init__(self, endpoint: str, reconnect_s: Optional[float] = None,
                 **kw):
        super().__init__(**kw)
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))
        self.reconnect_s = (config.env_float("DKTPU_STREAM_RECONNECT_S")
                            if reconnect_s is None else float(reconnect_s))
        self.reconnects = 0

    def _connect(self, start: int) -> Optional[socket.socket]:
        deadline = time.monotonic() + self.reconnect_s
        delay = 0.05
        while not self._stop.is_set():
            try:
                s = socket.create_connection(self.addr, timeout=5.0)
                s.sendall(json.dumps({"start": start}).encode() + b"\n")
                return s
            except OSError:
                if time.monotonic() >= deadline:
                    return None
                self._stop.wait(delay)
                delay = min(delay * 2, 1.0)
        return None

    def _recv_exact(self, s: socket.socket, n: int) -> bytes:
        chunks: list[bytes] = []
        got = 0
        while got < n:
            chunk = s.recv(n - got)
            if not chunk:
                raise ConnectionError("feed connection closed mid-frame")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def read(self, start_index: int = 0,
             skip: frozenset = frozenset()) -> Iterator[StreamRecord]:
        from distkeras_tpu import telemetry

        index = start_index
        conn = self._connect(index)
        while conn is not None and not self._stop.is_set():
            try:
                conn.settimeout(0.5)
                try:
                    head = self._recv_exact(conn, _LEN.size)
                except socket.timeout:
                    continue  # feed quiet; keep waiting (watchdog's job)
                (size,) = _LEN.unpack(head)
                if size == 0:
                    break
                conn.settimeout(10.0)
                payload = self._recv_exact(conn, size)
            except OSError:
                # Source kill: reconnect resuming at the next undelivered
                # index — retransmits only, no lost or duplicate records.
                conn.close()
                self.reconnects += 1
                telemetry.counter("stream.source_reconnects").add(1)
                conn = self._connect(index)
                continue
            rec = self._deliver(decode_record(payload, index), skip)
            index += 1
            if rec is not None:
                yield rec
        if conn is not None:
            conn.close()
