"""The claim queue, generalized: bounded schedules AND open-ended streams.

:class:`~distkeras_tpu.fleet.run.ElasticTraining` introduced the claim
queue for a *fixed* ``num_rounds x W`` work set — item identity was an
integer and "done" was a count. A live stream has neither: items arrive
forever (or until the feed says otherwise) and the only invariant is
that every *admitted* item is eventually committed exactly once. This
class carries both shapes so the elastic runtime and the streaming
runtime share one claim/requeue/commit discipline (and its tests):

* ``WorkQueue(total=N)`` — the bounded mode: items are the ordinals
  ``0..N-1``, claimed retry-first then frontier, exactly the original
  ElasticTraining bookkeeping.
* ``WorkQueue(max_pending=M)`` — the open mode: arbitrary items are
  :meth:`put` by a reader thread (blocking at ``M`` pending — the
  backpressure that keeps a fast feed from ballooning host memory),
  ``close_intake()`` marks end-of-stream, and ``done()`` means intake
  closed + nothing pending + nothing in flight.

In both modes :meth:`claim` blocks politely while other claimants are
still in flight: an item they requeue (eviction, lease lapse) must find
a worker, not a drained pool.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional


class WorkQueue:
    """Claim/requeue/commit bookkeeping shared by the elastic (bounded)
    and streaming (open-ended) runtimes. Thread-safe."""

    def __init__(self, total: Optional[int] = None,
                 max_pending: int = 64):
        self.total = total
        self.max_pending = max(1, int(max_pending))
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._retry: collections.deque = collections.deque()
        #: open mode: admitted-but-unclaimed items.
        self._pending: collections.deque = collections.deque()
        #: bounded mode: the frontier ordinal.
        self._next = 0
        self._inflight = 0
        self.committed = 0
        self._intake_closed = total is not None

    # -- producer side (open mode) ------------------------------------------

    def put(self, item, should_stop=None) -> bool:
        """Admit one item, blocking while ``max_pending`` are already
        waiting (backpressure on the reader). Returns False when
        ``should_stop()`` went true (or intake closed) before admission."""
        if self.total is not None:
            raise RuntimeError("put() is for open-ended queues; "
                               "bounded queues own their ordinals")
        with self._not_full:
            while len(self._pending) >= self.max_pending:
                if self._intake_closed or (should_stop and should_stop()):
                    return False
                self._not_full.wait(0.05)
            if self._intake_closed:
                return False
            self._pending.append(item)
            return True

    def close_intake(self) -> None:
        """No more items will arrive (end-of-stream, or shutdown)."""
        with self._not_full:
            self._intake_closed = True
            self._not_full.notify_all()

    # -- worker side ---------------------------------------------------------

    def claim(self, should_run):
        """The next work item: retries first, then fresh. Blocks while
        peers' claims are in flight (their requeue must find a taker);
        returns None when the work set is exhausted or ``should_run()``
        goes false."""
        while should_run():
            with self._lock:
                if self._retry:
                    self._inflight += 1
                    return self._retry.popleft()
                if self.total is not None:
                    if self._next < self.total:
                        i = self._next
                        self._next += 1
                        self._inflight += 1
                        return i
                    if self.committed >= self.total:
                        return None
                else:
                    if self._pending:
                        item = self._pending.popleft()
                        self._inflight += 1
                        self._not_full.notify_all()
                        return item
                    if self._intake_closed and self._inflight == 0:
                        return None
            time.sleep(0.01)
        return None

    def requeue(self, item) -> None:
        """Return a claimed-but-uncommitted item (eviction, crash unwind)
        for whichever claimant comes next."""
        with self._lock:
            self._inflight -= 1
            self._retry.append(item)

    def commit_one(self) -> None:
        with self._lock:
            self._inflight -= 1
            self.committed += 1

    def abandon(self, item=None) -> None:
        """Drop a claimed item permanently (shutdown paths that must not
        leave ``_inflight`` pinned)."""
        with self._lock:
            self._inflight -= 1

    # -- queries -------------------------------------------------------------

    def done(self) -> bool:
        with self._lock:
            if self.total is not None:
                return self.committed >= self.total
            return (self._intake_closed and not self._pending
                    and not self._retry and self._inflight == 0)

    def pending_count(self) -> int:
        with self._lock:
            if self.total is not None:
                return (self.total - self.committed)
            return len(self._pending) + len(self._retry) + self._inflight

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight
