"""The durable offset journal: exactly-once ingest across SIGKILL.

One JSON file (tmp + fsync + rename, sha256 sidecar — the checkpoint
meta idiom) records how far into the stream training has *provably*
gotten:

* ``frontier`` — every record with index < frontier has been folded into
  the PS center. Restart resumes the source here.
* ``ahead`` — records committed out of order past the frontier (elastic
  workers commit concurrently). Restart *skips* these.
* ``intents`` — per-worker in-flight commits: ``(seq, offset)`` journaled
  **before** the commit RPC is sent. This is what closes the ACK gap: a
  crash between the PS folding a commit and this journal recording it
  would otherwise replay the record. On restart, :meth:`resolve` compares
  each surviving intent's ``seq`` against the seq the PS reports as last
  folded for that worker (``join`` replies carry it, and the on-disk PS
  journal is the same evidence) — ``seq <= last_seq`` means the fold
  LANDED and only the ACK was lost, so the offset is marked committed
  without retraining; otherwise the intent is dropped and the record is
  re-read and re-sent **with a fresh seq the server has never folded**,
  so it folds exactly once either way.

The exactly-once argument, end to end: a record is folded iff one
``(wid, seq)`` commit carrying it was applied (PS dedup by per-worker
monotone seq rejects retransmits as ``duplicate``); the journal maps
offsets to seqs via intents and never advances the frontier past an
offset whose fold is unproven. What a crash can cost is bounded by the
un-ACKed window: at most one in-flight record per worker is *re-trained
into a fresh commit* — and only when the crash lands before the PS
folded it, so no record is ever folded twice and no ACKed record is
lost.

Corruption: the previous generation is kept (``.prev`` + its sidecar);
a torn or bit-flipped current file falls back to it — losing at most
the commits since the previous write, which restart then re-proves
against the PS journal via :meth:`resolve`.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Iterable, Optional

from distkeras_tpu.resilience.integrity import file_sha256


class OffsetJournal:
    """Durable record of stream ingest progress. Thread-safe: elastic
    workers journal intents/commits concurrently."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        # Reentrant: _mark_committed takes it itself so it is safe from
        # both the locked protocol methods and any future direct caller.
        self._lock = threading.RLock()
        self.frontier = 0
        self._ahead: set[int] = set()
        #: wid -> {"seq": int, "offset": int} — one in-flight commit per
        #: worker (the worker loop is serial per slot).
        self._intents: Dict[int, dict] = {}
        self.items_committed = 0
        #: newest event timestamp among committed records — the freshness
        #: anchor the checkpoint meta carries to the serving plane.
        self.last_event_ts: Optional[float] = None
        #: free-form runtime state that must survive restarts with the
        #: offsets (e.g. the index an injected drift began at — the fault
        #: one-shot is consumed pre-kill, the drifted world is not).
        self.meta: dict = {}

    # -- persistence --------------------------------------------------------

    def _snapshot(self) -> dict:
        return {
            "frontier": self.frontier,
            "ahead": sorted(self._ahead),
            "intents": {str(w): dict(v) for w, v in self._intents.items()},
            "items_committed": self.items_committed,
            "last_event_ts": self.last_event_ts,
            "meta": self.meta,
        }

    def _persist_locked(self) -> None:
        payload = json.dumps(self._snapshot()).encode()
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        # Keep the last good generation before replacing: a crash mid-write
        # (or a later bit flip) falls back to .prev instead of to zero.
        if os.path.exists(self.path):
            os.replace(self.path, self.path + ".prev")
            if os.path.exists(self.path + ".sha256"):
                os.replace(self.path + ".sha256", self.path + ".prev.sha256")
        os.replace(tmp, self.path)
        stmp = self.path + ".sha256.tmp"
        with open(stmp, "w") as f:
            f.write(file_sha256(self.path))
            f.flush()
            os.fsync(f.fileno())
        os.replace(stmp, self.path + ".sha256")

    def _load_one(self, path: str) -> Optional[dict]:
        try:
            with open(path + ".sha256") as f:
                want = f.read().strip()
            if file_sha256(path) != want:
                return None
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def load(self) -> bool:
        """Populate from disk (sha-verified; falls back to the previous
        generation on corruption). Returns whether a state was loaded."""
        with self._lock:
            state = self._load_one(self.path)
            if state is None:
                state = self._load_one(self.path + ".prev")
            if state is None:
                return False
            self.frontier = int(state.get("frontier", 0))
            self._ahead = {int(o) for o in state.get("ahead", ())}
            self._intents = {int(w): v
                             for w, v in (state.get("intents") or {}).items()}
            self.items_committed = int(state.get("items_committed", 0))
            self.last_event_ts = state.get("last_event_ts")
            self.meta = dict(state.get("meta") or {})
            return True

    # -- the two-phase commit protocol --------------------------------------

    def intent(self, wid: int, seq: int, offset: int) -> None:
        """Journal that worker ``wid`` is ABOUT to send commit ``seq``
        carrying record ``offset`` — written (and fsynced) before the RPC,
        so no fold can ever outrun the journal's knowledge of it."""
        with self._lock:
            self._intents[int(wid)] = {"seq": int(seq), "offset": int(offset)}
            self._persist_locked()

    def committed(self, wid: int, offset: int,
                  event_ts: Optional[float] = None) -> None:
        """Record that ``offset``'s fold was ACKed (applied or duplicate):
        clear the intent, advance the contiguous frontier."""
        with self._lock:
            self._intents.pop(int(wid), None)
            self._mark_committed(int(offset), event_ts)
            self._persist_locked()

    def _mark_committed(self, offset: int,
                        event_ts: Optional[float]) -> None:
        with self._lock:
            self.items_committed += 1
            if event_ts is not None and (self.last_event_ts is None
                                         or event_ts > self.last_event_ts):
                self.last_event_ts = float(event_ts)
            if offset == self.frontier:
                self.frontier += 1
                while self.frontier in self._ahead:
                    self._ahead.discard(self.frontier)
                    self.frontier += 1
            elif offset > self.frontier:
                self._ahead.add(offset)
            # offset < frontier: already counted before a crash-replay — the
            # resolve path never produces this, but stay idempotent.

    def resolve(self, last_seq_by_wid: Dict[int, int]) -> list[int]:
        """Reconcile surviving intents against what the PS provably folded
        (its per-worker last seq). Returns the offsets whose fold landed
        but whose ACK was lost — they are marked committed here and must
        NOT be re-read. Remaining intents are dropped: their records were
        never folded and will be re-read and re-sent under fresh seqs."""
        landed: list[int] = []
        with self._lock:
            for wid, rec in list(self._intents.items()):
                if int(last_seq_by_wid.get(wid, -1)) >= int(rec["seq"]):
                    self._mark_committed(int(rec["offset"]), None)
                    landed.append(int(rec["offset"]))
                del self._intents[wid]
            self._persist_locked()  # intents were dropped either way
        return landed

    # -- resume queries ------------------------------------------------------

    def start_offset(self) -> int:
        with self._lock:
            return self.frontier

    def skip_offsets(self) -> frozenset:
        """Offsets >= frontier already committed (out-of-order) — the
        source must not re-deliver them."""
        with self._lock:
            return frozenset(self._ahead)

    def committed_offsets_upto(self, n: int) -> set[int]:
        """Every offset < n this journal holds as committed — the
        cross-check set the resume tests compare against the PS journal."""
        with self._lock:
            return {o for o in range(min(self.frontier, n))} | {
                o for o in self._ahead if o < n}

    def set_meta(self, **kv) -> None:
        with self._lock:
            self.meta.update(kv)
            self._persist_locked()

    def offset_lag(self, items_read: int) -> int:
        with self._lock:
            return max(0, int(items_read) - self.items_committed)


def replayed_offsets(journal_before: Iterable[int],
                     delivered_after: Iterable[int]) -> set[int]:
    """Offsets a restarted run re-delivered despite the journal already
    holding them as committed — the exactly-once violation set (must be
    empty). A helper for the resume tests/smoke."""
    return set(journal_before) & set(delivered_after)
