"""StreamingTraining: the fleet-schedulable online-learning runtime.

:class:`~distkeras_tpu.fleet.run.ElasticTraining`'s claim-queue loop,
re-based on an **unbounded** work-item stream: records arrive from a
:class:`~distkeras_tpu.streaming.source` (through RoundFeeder staging,
so lookahead, stage retries, and the stall watchdog all apply), elastic
workers claim/train/commit them against the job's netps PS, and every
ACKed fold is journaled to the durable
:class:`~distkeras_tpu.streaming.journal.OffsetJournal` — SIGKILL the
process and the restart resumes at the last committed-to-PS offset with
zero replayed and zero lost records (docs/STREAMING.md walks the
argument).

Around the train loop, the rest of the online loop:

* per-commit windowed eval through :class:`DriftWatch` — loss
  divergence pages (``AlertManager``, page severity), fires
  **checkpoint-on-drift**, and times recovery;
* periodic center checkpoints (every ``checkpoint_every`` committed
  items, env ``DKTPU_STREAM_CKPT_EVERY``) whose meta carries the newest
  committed event timestamp — the serving registry turns that into the
  event-to-served-weight **freshness** measurement at hot-swap;
* the fleet runtime protocol (``ensure_started``/``worker_main``/
  ``progress``/``done``/``revoke``/``close``), so a streaming trainer is
  just another tenant a :class:`FleetScheduler` can colocate, shrink,
  and preempt.

:class:`StreamingSession` wraps a runtime in the Supervisor-compatible
trainer surface (``train()``/``checkpoint_dir``/``checkpoint_every``/
``resume``) so ``Supervisor`` retry-with-resume drives crash recovery
exactly as it does for batch trainers.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Optional

import numpy as np

from distkeras_tpu.netps.fold import check_discipline
from distkeras_tpu.netps.shards import make_ps_client
from distkeras_tpu.resilience import faults as _faults
from distkeras_tpu.runtime import config
from distkeras_tpu.streaming.evaluate import DriftWatch
from distkeras_tpu.streaming.items import WorkQueue
from distkeras_tpu.streaming.journal import OffsetJournal


class StreamingTraining:
    """One job's continual training off a live stream. See module
    docstring; constructor args mirror ElasticTraining's where shared.

    ``source`` is any object with ``read(start_index, skip)`` yielding
    :class:`StreamRecord`-shaped items and a ``close()``. ``journal``
    is an :class:`OffsetJournal`, a path, or None (no durability — tests
    only). ``max_items`` bounds the session (bench/tests): intake closes
    once that many records have been admitted *beyond* what the journal
    already holds committed.
    """

    def __init__(self, *, model, tx, loss_fn, source,
                 num_workers: int = 1,
                 discipline: str = "adag", alpha: float = 0.05,
                 seed: int = 0, compute_dtype=None, grad_accum: int = 1,
                 endpoint: Optional[str] = None, server=None,
                 lease_s: Optional[float] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None,
                 journal=None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 drift_watch: Optional[DriftWatch] = None,
                 max_items: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 resume: bool = False):
        self.model = model
        self.tx = tx
        self.loss_fn = loss_fn
        self.source = source
        self.num_workers = int(num_workers)
        self.discipline = check_discipline(discipline)
        self.alpha = float(alpha)
        self.seed = int(seed)
        self.compute_dtype = compute_dtype
        self.grad_accum = int(grad_accum)
        self._endpoint = endpoint
        self._lease_s = lease_s
        self._host, self._port = host, int(port)
        self._client_kw = dict(timeout=timeout, retries=retries,
                               backoff=backoff)
        self.server = server
        if server is not None and endpoint is None:
            self._endpoint = server.endpoint
        self.journal = (OffsetJournal(journal) if isinstance(journal, str)
                        else journal)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(config.env_int("DKTPU_STREAM_CKPT_EVERY")
                                    if checkpoint_every is None
                                    else checkpoint_every)
        self.drift = drift_watch or DriftWatch()
        self.drift.on_drift = self._on_drift
        self.max_items = max_items
        self.queue = WorkQueue(max_pending=int(
            config.env_int("DKTPU_STREAM_MAX_PENDING")
            if max_pending is None else max_pending))
        self.resume = bool(resume)
        self.errors: list = []
        self.losses: list[float] = []
        self._lock = threading.Lock()
        self._applied = 0
        self._stale: list[int] = []
        self._started = False
        self._closed = False
        self._loop_fn = None
        self._treedef = None
        self._init_leaves = None
        self._final_params = None
        self._reader_thread: Optional[threading.Thread] = None
        self._ckpt = None
        self._ckpt_lock = threading.Lock()
        self._ckpt_due = False
        self._last_ckpt_items = 0
        self.items_read = 0

    # -- runtime protocol ----------------------------------------------------

    def ensure_started(self) -> None:
        """Idempotent: resume state (journal + newest intact checkpoint),
        compile the window loop, launch the PS if owned, reconcile
        surviving commit intents against the PS, start the reader."""
        if self._started:
            return
        import jax

        from distkeras_tpu.workers import make_local_loop

        if self.journal is not None and self.resume:
            if self.journal.load():
                # The drifted world survives the restart even though the
                # fault one-shot does not.
                drift_from = self.journal.meta.get("drift_from")
                if drift_from is not None and getattr(
                        self.source, "drift_from", None) is None:
                    self.source.drift_from = int(drift_from)
        if self.checkpoint_dir and self.resume:
            self._restore_params()
        self._treedef = jax.tree.structure(self.model.params)
        self._init_leaves = [np.asarray(a, np.float32)
                             for a in jax.tree.leaves(self.model.params)]
        self._loop_fn = jax.jit(make_local_loop(
            self.model.module, self.loss_fn, self.tx,
            compute_dtype=self.compute_dtype,
            state_collections=self.model.state_collections,
            grad_accum=self.grad_accum,
            normalize_uint8=getattr(self.model, "normalize_uint8", True)))
        if self._endpoint is None:
            from distkeras_tpu.netps.server import PSServer

            self.server = PSServer(
                discipline=self.discipline, host=self._host,
                port=self._port, lease_s=self._lease_s).start()
            self._endpoint = self.server.endpoint
        self._resolve_intents()
        self._reader_thread = threading.Thread(
            target=self._reader, name="stream-reader", daemon=True)
        self._reader_thread.start()
        self._started = True

    def _restore_params(self) -> None:
        """Warm-start the model from the newest INTACT checkpoint —
        ``Trainer._resume_from_checkpoint``'s newest-first corruption
        fallback, for the params-only streaming state."""
        from distkeras_tpu import checkpoint as ckpt_mod
        from distkeras_tpu.checkpoint import Checkpointer

        steps = ckpt_mod.scan_steps(self.checkpoint_dir)
        if not steps:
            return
        cands = ckpt_mod.resume_candidates(
            steps, lambda s: ckpt_mod.read_meta(self.checkpoint_dir, s)
            is not None)
        ckpt = Checkpointer(self.checkpoint_dir)
        try:
            for step in cands:
                try:
                    params = ckpt.restore(self.model.params, step=step,
                                          verify=True)
                except Exception as e:  # noqa: BLE001 - walk to older step
                    import warnings

                    warnings.warn(
                        f"streaming resume: checkpoint step {step} "
                        f"unusable ({type(e).__name__}: {e}); falling back",
                        stacklevel=2)
                    continue
                self.model = self.model.with_params(params)
                with self._ckpt_lock:
                    self._last_ckpt_items = (self.journal.items_committed
                                             if self.journal else 0)
                return
        finally:
            ckpt.close()

    def _resolve_intents(self) -> None:
        """Close the ACK gap: for every worker that crashed with a commit
        in flight, ask the PS (a scoped rejoin as that worker id) for its
        last folded seq and settle the intent — landed folds are marked
        committed (never re-read), unlanded ones are dropped (re-read and
        re-committed under a fresh seq). Must complete before the reader
        computes its start/skip set."""
        if self.journal is None:
            return
        with self.journal._lock:
            wids = list(self.journal._intents)
        if not wids:
            return
        last: dict = {}
        for wid in wids:
            try:
                client = make_ps_client(self._endpoint, worker_id=wid,
                                        **self._client_kw)
                try:
                    client.join(init=self._init_leaves)
                    last[wid] = int(getattr(client, "_seq", -1))
                finally:
                    client.close()
            except Exception as e:  # noqa: BLE001 - PS down: drop intents
                self.errors.append(e)
        landed = self.journal.resolve(last)
        if landed:
            from distkeras_tpu import telemetry

            telemetry.event("stream_intents_resolved",
                            {"landed": sorted(landed)})

    @property
    def endpoint(self) -> Optional[str]:
        return self._endpoint

    @property
    def worker_slots(self) -> int:
        return self.num_workers

    def progress(self) -> int:
        return self._applied

    def done(self) -> bool:
        return self.queue.done()

    def revoke(self, worker_id: int) -> None:
        if self.server is not None:
            self.server.revoke(worker_id)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if getattr(self.source, "close", None) is not None:
            self.source.close()
        self.queue.close_intake()
        if self._reader_thread is not None:
            self._reader_thread.join(timeout=10.0)
        committed = (self.journal.items_committed if self.journal
                     else self.queue.committed)
        if self._endpoint is not None and committed > 0:
            try:
                with make_ps_client(self._endpoint,
                                    **self._client_kw) as obs:
                    leaves, _updates = obs.pull()
                self._final_params = self._unflatten(leaves)
            except Exception as e:  # noqa: BLE001 - surfaced via errors
                self.errors.append(e)
        with self._ckpt_lock:
            if self._ckpt is not None:
                try:
                    self._ckpt.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
                self._ckpt = None
        if self.server is not None:
            self.server.close()

    def result(self):
        if self._final_params is None:
            return self.model
        return self.model.with_params(self._final_params)

    # -- the reader ----------------------------------------------------------

    def _reader(self) -> None:
        """Source -> RoundFeeder staging -> claim queue. Runs the feeder's
        consumer loop, so the stall watchdog (and stage retry/injection)
        protect the stream path exactly as they do a BatchPlan's."""
        from distkeras_tpu import telemetry
        from distkeras_tpu.data.prefetch import RoundFeeder

        read_counter = telemetry.counter("stream.items_read")
        lag_gauge = telemetry.gauge("stream.offset_lag")
        start = self.journal.start_offset() if self.journal else 0
        skip = self.journal.skip_offsets() if self.journal else frozenset()
        budget = None
        if self.max_items is not None:
            done_already = (self.journal.items_committed if self.journal
                            else 0)
            budget = max(0, self.max_items - done_already)
        feeder = RoundFeeder(self.source.read(start, skip),
                             stage=lambda rec: rec, start_round=start)
        admitted = 0
        try:
            if budget == 0:
                return
            for _i, rec in feeder:
                self.items_read += 1
                read_counter.add(1)
                if not self.queue.put(rec, should_stop=lambda: self._closed):
                    return
                lag_gauge.set(self.queue.pending_count())
                admitted += 1
                if budget is not None and admitted >= budget:
                    return
        except BaseException as e:  # noqa: BLE001 - surfaced to the session
            self.errors.append(e)
        finally:
            feeder.close()
            self.queue.close_intake()
            if self.journal is not None and getattr(
                    self.source, "drift_from", None) is not None:
                # Persist the drifted-world marker for post-kill restarts.
                try:
                    self.journal.set_meta(drift_from=self.source.drift_from)
                except OSError:
                    pass

    # -- the worker loop -----------------------------------------------------

    def _unflatten(self, leaves):
        import jax

        return jax.tree.unflatten(self._treedef,
                                  [np.asarray(a) for a in leaves])

    def _on_drift(self, fast, slow) -> None:
        """Checkpoint-on-drift: flag an immediate save — the pre-adaptation
        snapshot is the rollback anchor (taken by the next committing
        worker, which holds a live client). The flag is deliberately set
        lock-free: blocking the commit path on an in-flight checkpoint
        save just to set a sticky bool would serialize drift detection
        behind Orbax I/O."""
        self._ckpt_due = True  # dk: disable=DK202 - sticky flag, cleared under _ckpt_lock

    def _commit_done(self, rec, loss: float, staleness: int, client) -> None:
        from distkeras_tpu import telemetry

        suffix = telemetry.label_suffix()
        if self.journal is not None:
            self.journal.committed(client.worker_id, rec.index,
                                   event_ts=rec.ts)
            if getattr(self.source, "drift_from", None) is not None and \
                    "drift_from" not in self.journal.meta:
                self.journal.set_meta(drift_from=self.source.drift_from)
        self.queue.commit_one()
        with self._lock:
            self._applied += 1
            self.losses.append(loss)
            if staleness >= 0:
                self._stale.append(int(staleness))
                if len(self._stale) > 256:
                    del self._stale[:-256]
            vals = list(self._stale)
        telemetry.counter(f"stream.items_committed{suffix}").add(1)
        telemetry.counter(f"fleet.commits{suffix}").add(1)
        if vals:
            telemetry.gauge(f"stream.staleness_mean{suffix}").set(
                round(float(np.mean(vals)), 3))
        self.drift.update(loss)
        self._maybe_checkpoint(client, force=self._ckpt_due)

    def _maybe_checkpoint(self, client, force: bool = False) -> None:
        if not self.checkpoint_dir:
            self._ckpt_due = False  # dk: disable=DK202 - no checkpointing: flag is inert
            return
        n = (self.journal.items_committed if self.journal
             else self.queue.committed)
        if not force and (self.checkpoint_every <= 0
                          or n < self._last_ckpt_items
                          + self.checkpoint_every):
            return
        from distkeras_tpu import telemetry

        with self._ckpt_lock:
            n = (self.journal.items_committed if self.journal
                 else self.queue.committed)
            if not force and n < self._last_ckpt_items + self.checkpoint_every:
                return
            self._ckpt_due = False
            if self._ckpt is None:
                from distkeras_tpu.checkpoint import Checkpointer

                self._ckpt = Checkpointer(self.checkpoint_dir,
                                          max_to_keep=5)
            with telemetry.span("stream.checkpoint"):
                leaves, _ = client.pull()
                params = self._unflatten(leaves)
                step = int(n)
                latest = self._ckpt.latest_step()
                if latest is not None and step <= latest:
                    step = latest + 1  # monotonicity across resumes
                event_ts = (self.journal.last_event_ts if self.journal
                            else None)
                meta = {"streaming": True, "items": int(n),
                        "event_ts": event_ts,
                        "drift": self.drift.detected_at is not None,
                        "saved_at": time.time()}
                if self.journal is not None:
                    meta["frontier"] = self.journal.frontier
                # wait=True: a streaming trainer checkpoints repeatedly
                # from commit threads — the next save must never race the
                # previous one's async finalize (and a SIGKILL right after
                # this line must still find a complete step on disk).
                self._ckpt.save(step, params, meta=meta, wait=True)
            self._last_ckpt_items = n
            telemetry.event("stream_checkpoint",
                            {"step": step, "items": int(n),
                             "event_ts": event_ts})

    def worker_main(self, worker_id: int, should_run) -> None:
        """One granted slot's loop: join -> (claim record; pull; K local
        steps; journal intent; commit; journal committed) until released
        or the stream drains — ElasticTraining's body with the claim
        queue open-ended and the offset journal in the commit path."""
        import jax

        from distkeras_tpu import telemetry

        w = int(worker_id)
        suffix = telemetry.label_suffix()
        elastic = self.discipline in ("aeasgd", "eamsgd")
        client = make_ps_client(self._endpoint, worker_id=w,
                                **self._client_kw)
        try:
            center_leaves, counter = client.join(init=self._init_leaves)
            params = self._unflatten(center_leaves)
            opt_state = self.tx.init(params)
            local = params if elastic else None
            mstate = (jax.tree.map(np.asarray, self.model.state)
                      if self.model.state is not None else None)
            base_key = jax.random.key(self.seed)
            rejoins_seen = client.rejoin_count
            readopt = False
            while True:
                rec = self.queue.claim(should_run)
                if rec is None:
                    break
                committed = False
                try:
                    plan = _faults.active_plan()
                    if plan is not None:
                        if plan.kill(rec.index):
                            # The mid-stream host kill: unmaskable, no
                            # cleanup — what the offset journal exists for.
                            os.kill(os.getpid(), signal.SIGKILL)
                        if plan.crash(rec.index):
                            from distkeras_tpu.resilience.errors import (
                                InjectedFault)

                            raise InjectedFault(
                                f"crash injected at stream item "
                                f"{rec.index} (DKTPU_FAULTS)")
                    with telemetry.span(f"stream.item{suffix}"):
                        net = _faults.active_net_plan()
                        if net is not None:
                            arg = net.fire("evict", rec.index)
                            if arg is not None:
                                lease = client.lease_s or 1.0
                                time.sleep(arg if arg > 0 else 2.0 * lease)
                        pulled_leaves, counter = client.pull()
                        if client.rejoin_count > rejoins_seen or readopt:
                            rejoins_seen = client.rejoin_count
                            readopt = False
                            if elastic:
                                local = self._unflatten(pulled_leaves)
                                opt_state = self.tx.init(local)
                        start = (local if elastic
                                 else self._unflatten(pulled_leaves))
                        xs = np.asarray(rec.xs)
                        ys = np.asarray(rec.ys)
                        rng = jax.random.fold_in(
                            jax.random.fold_in(base_key, w), rec.index)
                        new_params, opt_state, mstate, window_losses = \
                            self._loop_fn(start, opt_state, xs, ys, rng,
                                          mstate)
                        new_leaves = [np.asarray(a, np.float32)
                                      for a in jax.tree.leaves(new_params)]
                        pulled_np = [np.asarray(a, np.float32)
                                     for a in pulled_leaves]
                        if elastic:
                            e = [self.alpha * (n - p)
                                 for n, p in zip(new_leaves, pulled_np)]
                            local = self._unflatten(
                                [n - d for n, d in zip(new_leaves, e)])
                            delta = e
                        else:
                            delta = [n - p
                                     for n, p in zip(new_leaves, pulled_np)]
                            if self.discipline == "adag":
                                delta = [d / float(max(xs.shape[0], 1))
                                         for d in delta]
                        if self.journal is not None:
                            # Intent BEFORE the RPC: no fold outruns the
                            # journal's knowledge of it (see journal.py).
                            seq = int(getattr(client, "_seq", -1)) + 1
                            self.journal.intent(client.worker_id, seq,
                                                rec.index)
                        res = client.commit(delta, counter)
                        if res.evicted:
                            readopt = True
                        elif res.applied or res.duplicate:
                            committed = True
                            self._commit_done(
                                rec,
                                float(np.mean(np.asarray(window_losses))),
                                res.staleness, client)
                finally:
                    if not committed:
                        self.queue.requeue(rec)
                        telemetry.counter(f"stream.requeued{suffix}").add(1)
            client.leave()
        except BaseException as e:  # noqa: BLE001 - surfaced to the reaper
            self.errors.append(e)
            raise
        finally:
            client.close()


class StreamingSession:
    """Supervisor-compatible wrapper: ``factory(resume) -> a fresh
    StreamingTraining`` per attempt (re-entry safe by construction, like
    ``Trainer.train``'s per-call engine rebuild). ``checkpoint_dir`` /
    ``checkpoint_every`` / ``resume`` mirror the Trainer attributes the
    Supervisor consults; a crash mid-stream retries with ``resume=True``
    and the rebuilt runtime restores the newest intact checkpoint AND
    re-enters the stream at the journal's committed frontier."""

    def __init__(self, factory: Callable[[bool], StreamingTraining],
                 num_workers: int = 1,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1):
        self.factory = factory
        self.num_workers = int(num_workers)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.resume = False
        self.runtime: Optional[StreamingTraining] = None

    def train(self, dataframe=None, shuffle: bool = False):
        """Run the stream to exhaustion (or ``max_items``); returns the
        trained model. ``dataframe``/``shuffle`` exist for Trainer-surface
        compatibility (the Supervisor passes them) and are ignored — the
        source IS the data."""
        rt = self.factory(self.resume)
        self.runtime = rt
        rt.ensure_started()
        abort = threading.Event()
        threads = [threading.Thread(
            target=self._drive, args=(rt, w, abort),
            name=f"stream-worker-{w}", daemon=True)
            for w in range(self.num_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rt.close()
        if rt.errors:
            raise rt.errors[0]
        return rt.result()

    @staticmethod
    def _drive(rt: StreamingTraining, w: int, abort: threading.Event):
        try:
            rt.worker_main(w, lambda: not abort.is_set())
        except BaseException as e:  # noqa: BLE001 - recorded in rt.errors
            abort.set()
            if not isinstance(e, Exception):
                raise  # KeyboardInterrupt/SystemExit still propagate
