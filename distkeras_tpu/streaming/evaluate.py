"""Windowed online eval + drift handling for streaming training.

The batch world evaluates after an epoch; a stream has no epochs, so
quality is a pair of sliding windows over per-item training loss: a
**fast** window (recent items) against a **slow** window (the
established baseline) — the health plane's self-calibrating
fast-vs-slow drift idiom (:mod:`telemetry/health/sentinels`), applied
at item granularity where it can also *act*:

* **Page**: the ratio breaching routes through the shared
  :class:`~distkeras_tpu.telemetry.health.slo.AlertManager` at ``page``
  severity (``stream:loss_divergence``) — fire/clear hysteresis, typed
  alert events, and the page's flight dump all come with it.
* **Checkpoint-on-drift**: the fire transition invokes ``on_drift``
  (the runtime saves a pre-adaptation checkpoint — the rollback anchor
  and the forensics snapshot).
* **Recovery timing**: the clear transition records
  ``stream.recovery_seconds`` (drift detected -> loss back under the
  hysteresis) — the bench's time-to-recover metric — and invokes
  ``on_recover``.

The same windowed mean doubles as the serving registry's quality gate:
:meth:`DriftWatch.regression_gate` refuses a hot-swap candidate whose
held-out loss regressed past a floor over the incumbent's
(rollback-on-regression).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional

from distkeras_tpu.runtime import config
from distkeras_tpu.telemetry.health.slo import AlertManager


class WindowedEval:
    """Fast/slow sliding means over a scalar loss stream. Thread-safe
    (workers observe concurrently; the drift check reads)."""

    def __init__(self, fast: Optional[int] = None,
                 slow: Optional[int] = None):
        self.fast_n = int(config.env_int("DKTPU_STREAM_EVAL_FAST")
                          if fast is None else fast)
        self.slow_n = int(config.env_int("DKTPU_STREAM_EVAL_SLOW")
                          if slow is None else slow)
        self._fast: collections.deque = collections.deque(maxlen=self.fast_n)
        self._slow: collections.deque = collections.deque(maxlen=self.slow_n)
        self._lock = threading.Lock()
        self.count = 0

    def observe(self, loss: float) -> None:
        v = float(loss)
        with self._lock:
            self._fast.append(v)
            self._slow.append(v)
            self.count += 1

    def fast_mean(self) -> Optional[float]:
        with self._lock:
            return (sum(self._fast) / len(self._fast)) if self._fast else None

    def slow_mean(self) -> Optional[float]:
        with self._lock:
            return (sum(self._slow) / len(self._slow)) if self._slow else None


class DriftWatch:
    """The acting end of windowed eval: gauges, the page, the
    checkpoint-on-drift hook, and recovery timing. One instance per
    streaming runtime; :meth:`update` is called per committed item."""

    def __init__(self, alerts: Optional[AlertManager] = None,
                 window: Optional[WindowedEval] = None,
                 drift_factor: Optional[float] = None,
                 floor: float = 0.05,
                 on_drift: Optional[Callable] = None,
                 on_recover: Optional[Callable] = None):
        self.alerts = alerts or AlertManager()
        self.window = window or WindowedEval()
        self.drift_factor = float(
            config.env_float("DKTPU_STREAM_DRIFT_FACTOR")
            if drift_factor is None else drift_factor)
        self.floor = float(floor)
        self.on_drift = on_drift
        self.on_recover = on_recover
        self.drift_events = 0
        self.detected_at: Optional[float] = None
        self.last_recovery_s: Optional[float] = None

    @property
    def paging(self) -> bool:
        return self.alerts.is_active("stream:loss_divergence")

    def update(self, loss: float) -> Optional[str]:
        """Observe one committed item's loss; returns the alert
        transition ("fired"/"cleared") when one happened."""
        from distkeras_tpu import telemetry

        self.window.observe(loss)
        fast = self.window.fast_mean()
        slow = self.window.slow_mean()
        if fast is not None:
            telemetry.gauge("stream.eval.loss_fast").set(round(fast, 5))
        if slow is not None:
            telemetry.gauge("stream.eval.loss_slow").set(round(slow, 5))
        # Warmup guard: until the slow window outgrows the fast one, the
        # two means track each other by construction and can never vouch
        # for a baseline.
        mature = self.window.count > self.window.fast_n
        breaching = bool(
            mature and fast is not None and slow is not None
            and fast > self.floor and slow > 0
            and fast / slow > self.drift_factor)
        transition = self.alerts.update(
            "stream:loss_divergence", breaching, severity="page",
            message=(f"streaming eval loss diverged: fast window {fast} vs "
                     f"slow {slow} (> {self.drift_factor}x)"),
            value=fast)
        if transition == "fired":
            self.drift_events += 1
            self.detected_at = time.monotonic()
            telemetry.counter("stream.drift_events").add(1)
            telemetry.event("stream_drift_detected",
                            {"fast": fast, "slow": slow})
            if self.on_drift is not None:
                self.on_drift(fast, slow)
        elif transition == "cleared" and self.detected_at is not None:
            self.last_recovery_s = time.monotonic() - self.detected_at
            self.detected_at = None
            telemetry.gauge("stream.recovery_seconds").set(
                round(self.last_recovery_s, 3))
            telemetry.event("stream_drift_recovered",
                            {"seconds": round(self.last_recovery_s, 3)})
            if self.on_recover is not None:
                self.on_recover(self.last_recovery_s)
        return transition

    # -- rollback-on-regression gate -----------------------------------------

    def regression_gate(self, eval_fn: Callable,
                        regress_floor: Optional[float] = None) -> Callable:
        """A quality gate for :class:`~distkeras_tpu.serving.registry.
        ModelRegistry`: ``eval_fn(candidate_model) -> loss`` scores a
        hot-swap candidate on held-out recent data; the gate refuses it
        (returns False) when its loss regressed more than
        ``regress_floor`` (fractional, env ``DKTPU_STREAM_REGRESS_FLOOR``)
        over the best loss any accepted candidate achieved."""
        floor = float(config.env_float("DKTPU_STREAM_REGRESS_FLOOR")
                      if regress_floor is None else regress_floor)
        state = {"best": None}

        def gate(candidate, step: int) -> bool:
            from distkeras_tpu import telemetry

            loss = float(eval_fn(candidate))
            telemetry.gauge("stream.candidate_loss").set(round(loss, 5))
            best = state["best"]
            if best is not None and loss > best * (1.0 + floor):
                telemetry.event("stream_swap_rolled_back", {
                    "step": step, "loss": round(loss, 5),
                    "best": round(best, 5)})
                return False
            if best is None or loss < best:
                state["best"] = loss
            return True

        return gate
