"""The deterministic discrete-event core.

One heap, one virtual clock, one explicitly-threaded RNG. Determinism is
a contract, not an aspiration (pinned by ``tests/test_sim.py``): two runs
with the same seed and scenario are bit-identical because

* the event heap orders by ``(time, seq)`` — ``seq`` is a monotonically
  assigned tie-breaker, so two events scheduled for the same instant pop
  in scheduling order and callables are never compared;
* every random draw goes through ``engine.rng`` (one
  :class:`random.Random` seeded from the scenario seed /
  ``DKTPU_SIM_SEED``) — no module-global RNG state;
* nothing in this package reads a wall clock — the seams
  (``FleetScheduler(clock=...)``, ``MetricsHub(clock=...)``) put the
  real subsystems on :meth:`SimEngine.now` too.
"""

from __future__ import annotations

import heapq
import math
import random
from typing import Callable, Optional

from distkeras_tpu.runtime.config import env_int


class SimEngine:
    """The event loop: schedule with :meth:`at`/:meth:`after`, advance
    with :meth:`run`. ``current_thread`` is the cooperative stand-in the
    fleet driver binds while a scheduler-spawned "thread" body executes
    (see :class:`~distkeras_tpu.sim.fleet_driver.SimThread`)."""

    def __init__(self, seed: Optional[int] = None):
        self.seed = env_int("DKTPU_SIM_SEED") if seed is None else int(seed)
        self.rng = random.Random(self.seed)
        self._heap: list = []
        self._seq = 0
        self._now = 0.0
        self.events_run = 0
        self.current_thread = None

    def now(self) -> float:
        return self._now

    def clock(self) -> Callable[[], float]:
        """The virtual clock as a zero-arg callable — drop-in for the
        ``clock=`` seams on the scheduler and the metrics hub."""
        return self.now

    def at(self, t: float, fn: Callable, *args) -> None:
        """Schedule ``fn(*args)`` at virtual time ``t`` (clamped to now —
        the past is not schedulable)."""
        self._seq += 1
        heapq.heappush(self._heap, (max(float(t), self._now),
                                    self._seq, fn, args))

    def after(self, dt: float, fn: Callable, *args) -> None:
        self.at(self._now + max(0.0, float(dt)), fn, *args)

    def run(self, until: Optional[float] = None,
            max_events: int = 5_000_000) -> float:
        """Pop-and-fire until the heap drains (or passes ``until``);
        returns the final virtual time. ``max_events`` is a runaway
        backstop — a scenario that trips it has a scheduling loop bug."""
        while self._heap:
            t = self._heap[0][0]
            if until is not None and t > until:
                break
            t, _seq, fn, args = heapq.heappop(self._heap)
            self._now = t
            fn(*args)
            self.events_run += 1
            if self.events_run >= max_events:
                raise RuntimeError(
                    f"sim exceeded {max_events} events at t={self._now:.3f}"
                    " — runaway event loop")
        if until is not None and until > self._now:
            self._now = until
        return self._now

    def pending(self) -> int:
        return len(self._heap)

    def lognormal(self, mu: float, sigma: float,
                  cap: Optional[float] = None) -> float:
        """One lognormal draw from the engine RNG, optionally capped (a
        fitted tail must not schedule a commit in the next century)."""
        v = self.rng.lognormvariate(mu, sigma) if sigma > 0.0 else \
            math.exp(mu)
        return min(v, cap) if cap is not None else v
