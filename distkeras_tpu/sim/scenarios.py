"""What-if scenarios: the simulator driving the REAL control planes.

Every scenario here builds a :class:`~distkeras_tpu.sim.core.SimEngine`
and wires the *production* subsystems onto its virtual clock through
their injection seams — the actual :class:`~distkeras_tpu.fleet.
scheduler.FleetScheduler` (placement, quotas, gang floors, preemption,
restart budgets), the actual :class:`~distkeras_tpu.telemetry.health.
slo.SloEngine` / :class:`~distkeras_tpu.telemetry.health.sentinels.
Sentinels` over a fed :class:`~distkeras_tpu.telemetry.health.hub.
MetricsHub`, and the real staleness-counter rules via
:class:`~distkeras_tpu.sim.cluster.SimCenter`. Only transport and time
are simulated; the decisions under test are made by production code.

Each scenario returns a JSON-able dict with a ``checks`` map of named
invariants and ``ok = all(checks)``; the CLI (``python -m
distkeras_tpu.sim run <name>``) exits non-zero when a check fails, which
is how the CI ``sim-regression`` job consumes them. Runs are
deterministic per seed (pinned by ``tests/test_sim.py``): results carry
no wall-clock values.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

from distkeras_tpu.sim.cluster import LinkClass, SimCenter, TreeTopology
from distkeras_tpu.sim.core import SimEngine
from distkeras_tpu.sim.fleet_driver import SimJobRuntime, SimThreadFactory

#: sentinel file paths that never exist — scenario Sentinels must not
#: read whatever BENCH_SUMMARY.json happens to sit in the cwd.
_ABSENT = "__dktpu_sim_absent__.json"


def _direction_changes(series) -> int:
    """Shrink/expand thrash metric: sign flips of a granted-count
    series (one shrink-then-regrow episode costs 2)."""
    changes = 0
    last = 0
    for a, b in zip(series, series[1:]):
        d = (b > a) - (b < a)
        if d and last and d != last:
            changes += 1
        if d:
            last = d
    return changes


def _drive_scheduler(engine: SimEngine, sched, tick_s: float,
                     until: float,
                     on_tick: Optional[Callable[[], None]] = None) -> None:
    """Reschedule ``sched.tick()`` every virtual ``tick_s`` until every
    job is terminal (or the safety horizon passes)."""

    def tick() -> None:
        sched.tick()
        if on_tick is not None:
            on_tick()
        if sched.all_terminal() or engine.now() >= until:
            return
        engine.after(tick_s, tick)

    engine.after(0.0, tick)


def _round_time(mean_s: float, sigma: float = 0.3):
    mu = math.log(mean_s)
    cap = 5.0 * mean_s
    return lambda engine, _wid: engine.lognormal(mu, sigma, cap=cap)


# -- 1. preemption storm ----------------------------------------------------

def preemption_storm(workers: int = 1000, regions: int = 3,
                     seed: Optional[int] = None, tick_s: float = 0.5,
                     storm_at: float = 6.0, round_s: float = 0.4,
                     rounds_per_worker: int = 30) -> dict:
    """A high-priority gang lands per region mid-run: the real scheduler
    must shrink the running bases *to their gang floors and never below*,
    place the storm, then re-expand without thrashing — while the real
    SLO engine watches the per-region commit rate dip and recover.

    Invariants: zero floor violations, every job completes, bounded
    shrink/expand direction changes, exactly-once at every center, and
    the rate alert both fires during the storm and clears with
    hysteresis afterwards.
    """
    from distkeras_tpu.fleet.job import FleetJob
    from distkeras_tpu.fleet.scheduler import FleetScheduler
    from distkeras_tpu.telemetry.health.hub import MetricsHub
    from distkeras_tpu.telemetry.health.slo import (
        AlertManager, SloEngine, SloSpec)

    engine = SimEngine(seed)
    base_max = workers // regions           # 333 at the 1000/3 scale
    base_min = max(1, workers // 10)        # the gang floor: 100
    storm_gang = max(1, workers // 10)      # one storm gang per region
    quota = base_max + storm_gang + workers // 100
    healthy_rate = base_max / round_s

    sched = FleetScheduler(
        capacity=workers,
        quotas={f"region-{r}": quota for r in range(regions)},
        tick_s=tick_s, preempt_grace=1.0, max_restarts=3,
        clock=engine.clock(), thread_factory=SimThreadFactory(engine))
    hub = MetricsHub(targets={}, interval=tick_s, ring=4096, down_after=3,
                     use_registry=False, clock=engine.clock())
    slo = SloEngine(
        [SloSpec(name="fleet-rate", metric="fleet.commit_rate",
                 stat="value", min=0.84 * healthy_rate,
                 fast_s=2 * tick_s, slow_s=4 * tick_s,
                 severity="ticket")],
        alerts=AlertManager(clear_after=2))

    bases = []
    for r in range(regions):
        rt = SimJobRuntime(engine, f"base-{r}", _round_time(round_s),
                           rounds_target=base_max * rounds_per_worker,
                           center=SimCenter())
        job = sched.submit(FleetJob(
            f"base-{r}", f"region-{r}", rt, priority=0,
            min_gang=base_min, max_workers=base_max))
        bases.append((job, rt))
    storms = []

    def submit_storm() -> None:
        for r in range(regions):
            rt = SimJobRuntime(engine, f"storm-{r}",
                               _round_time(round_s),
                               rounds_target=storm_gang * 8,
                               center=SimCenter())
            job = sched.submit(FleetJob(
                f"storm-{r}", f"region-{r}", rt, priority=10,
                min_gang=storm_gang, max_workers=storm_gang))
            storms.append((job, rt))

    engine.after(storm_at, submit_storm)

    last_progress = {r: 0 for r in range(regions)}

    def on_tick() -> None:
        now = engine.now()
        stats = sched.stats()
        any_base_running = False
        for r, (job, rt) in enumerate(bases):
            rt.granted_series.append(stats[job.job_id]["granted"])
            done = rt.progress()
            rate = (done - last_progress[r]) / tick_s
            last_progress[r] = done
            if not rt.done() and not rt.closed:
                any_base_running = True
                hub.feed(f"region-{r}", "fleet.commit_rate", rate,
                         role="fleet")
        # evaluate only in steady state: after the ramp's slow window
        # fills, and not on the final drain (rate -> 0 is completion,
        # not a breach)
        if any_base_running and now >= 3.0:
            slo.evaluate(hub)

    _drive_scheduler(engine, sched, tick_s, until=120.0, on_tick=on_tick)
    engine.run()
    sched.close()

    stats = sched.stats()
    thrash = {job.job_id: _direction_changes(rt.granted_series)
              for job, rt in bases}
    alerts = slo.alerts
    fired_keys = [h["key"] for h in alerts.history if h["event"] == "fired"]
    # the storm's capacity shortfall: slots the bases must surrender
    # (victim choice is pool-wide priority order, not per-region)
    shortfall = max(0, regions * storm_gang
                    - (workers - regions * base_max))
    preempted = sum(stats[j.job_id]["preemptions"] for j, _rt in bases)
    checks = {
        "all_done": all(s["state"] == "done" for s in stats.values()),
        "floors_never_violated": sched.floor_violations == 0,
        "storm_preempted_bases": preempted >= max(1, shortfall),
        "bases_reexpanded": all(
            stats[j.job_id]["expands"] >= 1 for j, _rt in bases),
        "no_thrash": all(v <= 8 for v in thrash.values()),
        "exactly_once": all(rt.center.exactly_once()
                            for _j, rt in bases + storms),
        "alert_fired_during_storm": alerts.fired_total >= 1,
        "alerts_bounded": alerts.fired_total <= 2,
        "alerts_cleared": (alerts.cleared_total == alerts.fired_total
                           and not alerts.active()),
    }
    return {
        "scenario": "preemption_storm", "seed": engine.seed,
        "workers": workers, "regions": regions,
        "virtual_s": round(engine.now(), 3), "events": engine.events_run,
        "stats": stats, "thrash": thrash,
        "alerts": {"fired": alerts.fired_total,
                   "cleared": alerts.cleared_total,
                   "keys": sorted(set(fired_keys))},
        "checks": checks, "ok": all(checks.values()),
    }


# -- 2. failover cascade ----------------------------------------------------

def failover_cascade(workers: int = 120, seed: Optional[int] = None,
                     tick_s: float = 0.5, round_s: float = 0.3) -> dict:
    """Crash waves + two full PS outages: the hub's fed liveness flips
    the endpoint down, the real scheduler's health pass drains-to-requeue
    the job (once per outage), the center fails over (epoch bump, dedup
    carried), and crashed workers restart against the real budget — some
    crashes lose the ack of an applied commit, so the restarted worker
    retransmits and the center's dedup must absorb the duplicate.

    Invariants: epochs nondecreasing across promotions, exactly-once at
    the center (value conservation to the last bit), exactly one requeue
    per outage, and the job still completes.
    """
    from distkeras_tpu.fleet.job import FleetJob
    from distkeras_tpu.fleet.scheduler import FleetScheduler
    from distkeras_tpu.telemetry.health.hub import (
        MetricsHub, unregister_target)

    engine = SimEngine(seed)
    center = SimCenter(discipline="downpour")
    rt = SimJobRuntime(engine, "train", _round_time(round_s),
                       rounds_target=workers * 65, center=center)
    hub = MetricsHub(targets={}, interval=tick_s, ring=4096, down_after=3,
                     use_registry=False, clock=engine.clock())
    sched = FleetScheduler(
        capacity=workers + workers // 4, quotas=None, tick_s=tick_s,
        preempt_grace=1.0, max_restarts=10 * workers, health_hook=hub,
        clock=engine.clock(), thread_factory=SimThreadFactory(engine))
    outages = [(12.0, 14.0), (20.0, 22.0)]

    def in_outage(t: float) -> bool:
        return any(a <= t < b for a, b in outages)

    def on_tick() -> None:
        if in_outage(engine.now()):
            hub.feed_miss(rt.endpoint, role="ps")
        else:
            hub.feed(rt.endpoint, "up", 1.0, role="ps")

    def crash_wave(frac: float) -> None:
        live = sorted(wid for wid, st in rt._workers.items()
                      if not st.finished)
        step = max(1, int(1 / frac))
        for i, wid in enumerate(live[::step]):
            rt.crash(wid, lose_ack=(i % 2 == 0))

    try:
        job = sched.submit(FleetJob(
            "train", "acme", rt, priority=0,
            min_gang=max(1, workers // 3), max_workers=workers))
        for t in (3.0, 6.0, 9.0):
            engine.after(t, crash_wave, 0.10)
        for _t0, t1 in outages:
            # the standby takes over just before the endpoint recovers
            engine.after(t1 - 0.1, center.promote)
        _drive_scheduler(engine, sched, tick_s, until=120.0,
                         on_tick=on_tick)
        engine.run()
        sched.close()
    finally:
        unregister_target(rt.endpoint)

    stats = sched.stats()[job.job_id]
    checks = {
        "job_done": stats["state"] == "done",
        "epochs_nondecreasing": (
            center.epoch_history
            == sorted(center.epoch_history)),
        "both_failovers_promoted": center.epoch == len(outages),
        "one_requeue_per_outage": stats["requeues"] == len(outages),
        "crashes_restarted": (rt.crashes > 0
                              and stats["restarts"] >= 1),
        "exactly_once": center.exactly_once(),
        "value_conserved": (center.center_value()
                            == float(center.commits_total)),
        "duplicates_absorbed": (rt.resends_expected >= 1
                                and 1 <= center.duplicates
                                <= rt.resends_expected),
    }
    return {
        "scenario": "failover_cascade", "seed": engine.seed,
        "workers": workers, "virtual_s": round(engine.now(), 3),
        "events": engine.events_run, "stats": stats,
        "center": {"epochs": center.epoch_history,
                   "commits": center.commits_total,
                   "duplicates": center.duplicates,
                   "value": center.center_value(),
                   "max_staleness": center.max_staleness},
        "crashes": rt.crashes, "resends_expected": rt.resends_expected,
        "checks": checks, "ok": all(checks.values()),
    }


# -- 3. region partition ----------------------------------------------------

def region_partition(workers: int = 960, seed: Optional[int] = None,
                     rounds: int = 40, work_s: float = 0.2,
                     partition=(3.0, 6.0), levels=None,
                     flush_s: float = 0.05) -> dict:
    """An N-level aggregation tree (host -> pool -> region, per-link
    codec/latency classes) with one region's uplink black-holed for a
    window. During the partition that region's workers run on a cached
    pull counter (the overlap window), its aggregators queue flushes,
    and on heal the queue drains plus ONE duplicate retransmit of the
    last flush — the root's dedup (real counter rules) must absorb it.

    Invariants: value conservation at the root (every worker commit
    accounted, none double-folded), exactly-once, and the partitioned
    region's staleness spiking above the healthy regions'.

    ``levels``/``flush_s`` re-shape the tree without forking the
    scenario: :func:`~distkeras_tpu.sim.calibrate.tree_parity` re-fits
    this scenario to a LIVE traced tree's shape (its fanouts, flush
    cadence, and measured commit period) and asserts agreement. The
    defaults are the 1000-worker what-if unchanged.
    """
    engine = SimEngine(seed)
    center = SimCenter(discipline="downpour")
    if levels is None:
        levels = [
            ("host", 8,
             LinkClass("host", 0.0002, jitter=0.10, codec="int8")),
            ("pool", 4, LinkClass("pool", 0.001, jitter=0.10,
                                  codec="bf16")),
            ("region", 10, LinkClass("region", 0.005, jitter=0.10,
                                     codec="none")),
        ]
    topo = TreeTopology(workers, levels, flush_s=flush_s)
    region_level = len(levels) - 1
    regions = len(topo.aggregators[region_level])
    part_region = 1 if regions > 1 else 0
    t0, t1 = partition
    topo.partition(region_level, part_region, t0, t1)

    # per-region-aggregator commit identity at the root (the root's
    # clients ARE the region aggregators), + queued flushes per region
    agg_seq = {g: 0 for g in range(regions)}
    queued: Dict[int, list] = {g: [] for g in range(regions)}
    cached_pull = {g: center.pull() for g in range(regions)}
    region_staleness: Dict[int, int] = {}
    mu_work = math.log(work_s)

    def root_commit(g: int, seq: int, payload: dict) -> None:
        res = center.commit(10_000 + g, seq, payload["pulled"],
                            payload["value"])
        if res["applied"]:
            region_staleness[g] = max(region_staleness.get(g, 0),
                                      res["staleness"])

    last_deliver = {g: 0.0 for g in range(regions)}

    def send_root(g: int, seq: int, payload: dict) -> None:
        """One in-order uplink delivery (the wire is a FIFO stream per
        connection — jitter must not reorder an aggregator's seqs)."""
        link = topo.level_links(region_level)
        t = max(engine.now() + link.sample(engine), last_deliver[g])
        last_deliver[g] = t
        engine.at(t, root_commit, g, seq, payload)

    def uplink_send(g: int, payload: dict) -> None:
        """Region g's uplink: deliver, or queue under partition and
        drain (+ one duplicate retransmit) on heal."""
        if topo.link_down(region_level, g, engine.now()):
            if not queued[g]:
                heal = topo.heals_at(region_level, g, engine.now())
                engine.at(heal, drain_queue, g)
            queued[g].append(payload)
            return
        seq = agg_seq[g]
        agg_seq[g] += 1
        send_root(g, seq, payload)

    def drain_queue(g: int) -> None:
        backlog, queued[g] = queued[g], []
        for payload in backlog:
            seq = agg_seq[g]
            agg_seq[g] += 1
            send_root(g, seq, payload)
        if backlog:
            # the retransmit the sender could not distinguish from a
            # lost ack: same seq as the last flush -> root dedup absorbs
            send_root(g, agg_seq[g] - 1, backlog[-1])

    def hop(level: int, g: int, payload: dict) -> None:
        """One flush arriving at level ``level``'s aggregator ``g``."""
        agg = topo.aggregators[level][g]
        out = agg.fold(engine.now(), payload["pulled"], payload["value"])
        if out is None:
            return
        if level == region_level:
            uplink_send(g, out)
        else:
            nxt = level + 1
            link = topo.level_links(nxt)
            engine.after(link.sample(engine), hop, nxt,
                         g // topo.levels[nxt][1], out)

    done = {w: 0 for w in range(workers)}

    def worker_round(w: int) -> None:
        g = topo.group_of(w, region_level)
        if topo.link_down(region_level, g, engine.now()):
            pulled = cached_pull[g]   # the overlap window: stale counter
        else:
            pulled = cached_pull[g] = center.pull()
        engine.after(engine.lognormal(mu_work, 0.3, cap=5.0 * work_s),
                     commit_round, w, pulled)

    def commit_round(w: int, pulled) -> None:
        # the commit is fire-and-forget into the tree; the worker's next
        # round begins immediately (it does not wait for the root fold)
        engine.after(topo.level_links(0).sample(engine), hop, 0,
                     topo.group_of(w, 0), {"pulled": pulled, "value": 1.0})
        done[w] += 1
        if done[w] < rounds:
            worker_round(w)

    for w in range(workers):
        engine.after(engine.rng.uniform(0.0, work_s), worker_round, w)
    engine.run()

    # final drain: every partial accumulation flushes (conservation)
    for level in range(len(levels)):
        for g, agg in sorted(topo.aggregators[level].items()):
            out = agg.take(engine.now())
            if out is None:
                continue
            if level == region_level:
                uplink_send(g, out)
            else:
                nxt = level + 1
                engine.after(topo.level_links(nxt).sample(engine), hop,
                             nxt, g // topo.levels[nxt][1], out)
            engine.run()
    engine.run()

    expected = float(workers * rounds)
    healthy_max = max((s for g, s in region_staleness.items()
                       if g != part_region), default=0)
    checks = {
        "value_conserved": center.center_value() == expected,
        "exactly_once": center.exactly_once(),
        "retransmit_deduped": center.duplicates >= 1,
        "staleness_spiked_in_partition": (
            region_staleness.get(part_region, 0) > healthy_max),
    }
    return {
        "scenario": "region_partition", "seed": engine.seed,
        "workers": workers, "regions": regions,
        "partitioned_region": part_region,
        "virtual_s": round(engine.now(), 3), "events": engine.events_run,
        "root_commits": center.commits_total,
        "duplicates": center.duplicates,
        "center_value": center.center_value(),
        "staleness_by_region": {str(g): region_staleness.get(g, 0)
                                for g in range(regions)},
        "checks": checks, "ok": all(checks.values()),
    }


# -- 4. alert storm ---------------------------------------------------------

def alert_storm(seed: Optional[int] = None, regions: int = 3,
                targets_per_region: int = 20, sweep_s: float = 2.0,
                horizon_s: float = 150.0) -> dict:
    """60 fed targets through healthy -> breach -> recover phases under
    the real SLO engine, sentinels, and alert manager. Two regions
    breach their latency objective and five targets go silent (the
    ``target_down`` page sentinel); recovery must clear everything.

    Invariants: pages/tickets bounded (one alert per breaching
    condition, no flapping — each key fires exactly once), and every
    alert clears through hysteresis by the end.
    """
    from distkeras_tpu.telemetry.health.hub import MetricsHub
    from distkeras_tpu.telemetry.health.sentinels import Sentinels
    from distkeras_tpu.telemetry.health.slo import (
        AlertManager, SloEngine, SloSpec)

    engine = SimEngine(seed)
    hub = MetricsHub(targets={}, interval=sweep_s, ring=4096, down_after=3,
                     use_registry=False, clock=engine.clock())
    alerts = AlertManager(clear_after=2)
    slo = SloEngine(
        [SloSpec(name=f"latency-region-{r}", metric="serving.latency",
                 stat="mean", max=0.25, fast_s=2 * sweep_s,
                 slow_s=6 * sweep_s, severity="ticket",
                 target=f"region-{r}-*") for r in range(regions)],
        alerts=alerts)
    sentinels = Sentinels(alerts=alerts, bench_summary=_ABSENT,
                          bench_pin=_ABSENT)
    names = [f"region-{r}-t{i}" for r in range(regions)
             for i in range(targets_per_region)]
    silent = names[:5]                      # go dark during the breach
    breach_regions = {f"region-{r}" for r in range(min(2, regions))}
    b0, b1 = 0.3 * horizon_s, 0.7 * horizon_s

    def sweep() -> None:
        now = engine.now()
        breaching = b0 <= now < b1
        for name in names:
            if breaching and name in silent:
                hub.feed_miss(name, role="serving")
                continue
            region = name.rsplit("-", 1)[0]
            lat = 0.10 + 0.02 * engine.rng.random()
            if breaching and region in breach_regions:
                lat = 0.40 + 0.05 * engine.rng.random()
            hub.feed(name, "serving.latency", lat, role="serving")
        slo.evaluate(hub)
        sentinels.evaluate(hub)
        if now + sweep_s <= horizon_s:
            engine.after(sweep_s, sweep)

    engine.after(0.0, sweep)
    engine.run()

    fired = [h for h in alerts.history if h["event"] == "fired"]
    fired_keys = [h["key"] for h in fired]
    expected = len(breach_regions) + len(silent)
    checks = {
        "alerts_fired": alerts.fired_total >= expected,
        "alerts_bounded": alerts.fired_total <= expected + 2,
        "no_flapping": len(fired_keys) == len(set(fired_keys)),
        "pages_are_target_down": all(
            h["key"].startswith("target_down:") for h in fired
            if h["severity"] == "page"),
        "all_cleared": (alerts.cleared_total == alerts.fired_total
                        and not alerts.active()),
    }
    return {
        "scenario": "alert_storm", "seed": engine.seed,
        "targets": len(names), "virtual_s": round(engine.now(), 3),
        "events": engine.events_run,
        "alerts": {"fired": alerts.fired_total,
                   "cleared": alerts.cleared_total,
                   "keys": sorted(set(fired_keys))},
        "attainment": slo.attainment(),
        "checks": checks, "ok": all(checks.values()),
    }


# -- 5. crossover (calibration gate as a scenario) --------------------------

def crossover(seed: Optional[int] = None, summary=None) -> dict:
    """The flat->hier crossover replay against the bench curve (see
    :func:`distkeras_tpu.sim.calibrate.hier_crossover`)."""
    from distkeras_tpu.sim.calibrate import hier_crossover

    out = hier_crossover(summary=summary,
                         seed=0 if seed is None else seed)
    out["scenario"] = "crossover"
    out["checks"] = {
        "held_out_within_band": bool(out["within_band"]),
        "crossover_reproduced": bool(out["crossover_reproduced"]),
    }
    out["ok"] = all(out["checks"].values())
    return out


SCENARIOS: Dict[str, Callable[..., dict]] = {
    "preemption_storm": preemption_storm,
    "failover_cascade": failover_cascade,
    "region_partition": region_partition,
    "alert_storm": alert_storm,
    "crossover": crossover,
}


def run_scenario(name: str, **kwargs) -> dict:
    fn = SCENARIOS.get(name)
    if fn is None:
        raise ValueError(
            f"unknown scenario {name!r}; known: {sorted(SCENARIOS)}")
    return fn(**kwargs)
