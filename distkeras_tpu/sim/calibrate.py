"""Calibration gates: replaying measured deployments through the sim.

Three replays keep the simulator honest:

* :func:`predict_throughput` / :func:`sim_drift` — replay a *traced*
  loopback deployment (bench config #8's data plane): fit the timing
  model from its trace stream (:class:`~distkeras_tpu.sim.model.
  TimingModel`), run the discrete-event replay (workers alternating
  fitted work gaps and commit paths against one serialized fold
  resource — queueing emerges from contention, it is never sampled),
  and compare predicted to measured throughput. ``bench.py`` publishes
  the ratio as the ``sim_drift`` block in BENCH_SUMMARY.json so the
  bench-regression sentinel watches calibration rot like any other
  regression.

* :func:`hier_crossover` — replay the bench ``hier_curve`` (flat vs
  hierarchical topology at W ∈ {1, 2, 4}): calibrate the serialized
  root-fold service from the **flat W ∈ {1, 2}** points (flat W=4 held
  out), and split the hier path into a per-commit aggregator cost plus a
  per-flush root cost from the hier curve's **endpoints** (W=1, where
  every commit flushes, and the max-W point, where fan-in batching
  amortizes the root visit — the root-commit counts in the summary pin
  the flush ratios). The middle hier point is then genuinely predicted:
  the DES runs the real :class:`~distkeras_tpu.sim.cluster.
  SimAggregator` flush policy (fan-in OR age), so the batching
  amortization — and therefore the flat->hier crossover — *emerges*
  rather than being interpolated. The gate asserts every held-out
  prediction lands within the band AND that the predicted hier/flat
  throughput ratio crosses the flip threshold at the measured crossover
  (W=4, matching ``recommended_topology``'s ``DKTPU_TUNE_HIER_FANIN``
  default) with a root-ingress cut that justifies the topology.

* :func:`tree_parity` — re-fit the ``region_partition`` scenario from a
  LIVE traced aggregation-tree run (its fanouts, flush cadence, commit
  period, and partition window) and assert the sim reproduces the root
  ingress cut and the partitioned region's staleness spike within the
  band — the gate that licenses the tree what-ifs at 1000-worker scale.
  The tree chaos smoke publishes it as the ``tree_parity`` block in
  BENCH_SUMMARY.json; ``sim calibrate --tree-live live.json`` replays
  one from a recorded live dict.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from distkeras_tpu.runtime.config import env_float
from distkeras_tpu.sim.cluster import SimAggregator
from distkeras_tpu.sim.core import SimEngine
from distkeras_tpu.sim.model import TimingModel

#: hier/flat throughput ratio at which the topology recommendation flips
#: (the tuner flips on fan-in ≥ DKTPU_TUNE_HIER_FANIN = 4; on the bench
#: curve that corresponds to the ratio entering this band while the
#: root-ingress cut pays for the residual gap).
RATIO_BAND = 0.85
#: minimum flat/hier root-commit-rate cut that justifies hier at the
#: crossover point (the whole point of the topology: root ingress).
INGRESS_CUT_MIN = 2.5


def _band_pct(band_pct: Optional[float]) -> float:
    return env_float("DKTPU_SIM_BAND_PCT") if band_pct is None \
        else float(band_pct)


def replay_serialized(model: TimingModel, workers: int, rounds: int,
                      seed: int = 0) -> dict:
    """The deployment replay: ``workers`` event-driven workers, each
    alternating a fitted work gap + client-side commit half (encode +
    wire) with a visit to ONE serialized server resource (service =
    fold + fsync samples); the ack closes the round. Returns the virtual
    wall time and commit count."""
    eng = SimEngine(seed)
    server_free = [0.0]
    counts = {w: 0 for w in range(workers)}
    last_done = [0.0]

    def begin(w: int) -> None:
        eng.after(model.sample_work(eng)
                  + model.sample_commit_client(eng), arrive, w)

    def arrive(w: int) -> None:
        start = max(eng.now(), server_free[0])
        server_free[0] = start + model.sample_service(eng)
        eng.at(server_free[0] + model.sample_ack(eng), finish, w)

    def finish(w: int) -> None:
        counts[w] += 1
        last_done[0] = max(last_done[0], eng.now())
        if counts[w] < rounds:
            begin(w)

    for w in range(workers):
        begin(w)
    eng.run()
    commits = sum(counts.values())
    wall = last_done[0]
    return {"wall_s": wall, "commits": commits,
            "commits_per_sec": (commits / wall) if wall > 0 else None}


def predict_throughput(records: Optional[list] = None,
                       model: Optional[TimingModel] = None,
                       workers: Optional[int] = None,
                       rounds: Optional[int] = None,
                       tokens_per_round: Optional[float] = None,
                       seed: int = 0) -> dict:
    """Predict a traced deployment's throughput by replaying it. Worker
    count and per-worker rounds default to what the trace itself shows
    (distinct commit-root wids / commits per wid)."""
    from distkeras_tpu.telemetry.tracing import analysis

    if model is None:
        model = TimingModel.from_records(records or [])
    if workers is None or rounds is None:
        wids = {root.get("wid")
                for _t, root, _d, _e in analysis.commit_paths(records or [])
                if root.get("wid") is not None}
        if workers is None:
            workers = max(1, len(wids))
        if rounds is None:
            rounds = max(1, model.commits // max(1, workers))
    out = replay_serialized(model, workers, rounds, seed=seed)
    out.update({"workers": workers, "rounds": rounds,
                "model": model.describe()})
    if tokens_per_round is not None and out["wall_s"] > 0:
        out["tokens_per_sec"] = (tokens_per_round * out["commits"]
                                 / out["wall_s"])
    return out


def sim_drift(records: list, measured_tokens_per_sec: float,
              tokens_per_round: float, workers: Optional[int] = None,
              rounds: Optional[int] = None,
              band_pct: Optional[float] = None, seed: int = 0) -> dict:
    """The BENCH_SUMMARY ``sim_drift`` block: predicted/measured
    throughput ratio for the traced deployment, banded so the
    bench-regression sentinel can flag calibration rot."""
    band = _band_pct(band_pct)
    pred = predict_throughput(records, workers=workers, rounds=rounds,
                              tokens_per_round=tokens_per_round, seed=seed)
    predicted = pred.get("tokens_per_sec")
    ratio = (predicted / measured_tokens_per_sec
             if predicted and measured_tokens_per_sec else None)
    return {
        "metric": "sim_predicted_vs_measured_tokens_per_sec",
        "value": round(ratio, 4) if ratio is not None else None,
        "predicted_tokens_per_sec": (round(predicted, 1)
                                     if predicted else None),
        "measured_tokens_per_sec": round(measured_tokens_per_sec, 1),
        "band_pct": band,
        "within_band": (abs(ratio - 1.0) <= band / 100.0
                        if ratio is not None else None),
        "workers": pred["workers"], "rounds": pred["rounds"],
        "sim_commits": pred["commits"],
    }


# -- the live-tree region-partition replay ----------------------------------

def tree_parity(live: dict, band_pct: Optional[float] = None,
                seed: int = 0) -> dict:
    """The aggregation-tree calibration gate: re-fit the
    ``region_partition`` scenario from a LIVE traced tree run and assert
    the sim reproduces the two load-bearing shapes — the root ingress
    cut (absorbed worker commits per root fold) and the partitioned
    region's staleness spike — within the band.

    ``live`` is the measured run: ``workers``, ``fanouts`` (bottom-up
    interior fanouts, e.g. ``[2]`` for a 2-region/one-tier tree),
    ``rounds`` per worker, ``work_s`` (the fitted mean per-worker commit
    period — wall / rounds), ``flush_s`` (the tree nodes' flush
    interval), ``partition`` ``(t0, t1)`` in run-relative seconds, and
    the two measured shapes: ``ingress_cut`` (total absorbed / total
    root folds from the tree) and ``staleness_spike`` (the partitioned
    region's MAX root-fold staleness — both systems pin it to partition
    duration x healthy root update rate, so it transfers; the
    partitioned/healthy RATIO would instead ride the noisy tail question
    of whether some healthy flush happens to interleave the heal drain).
    The spike comparison is +1-regularized so a zero-staleness run still
    ratios. Optional: ``link_latency_s`` (default 1 ms), ``codec``
    (uplink codec class, default ``none``).

    Both systems run the SAME structure — fan-in-or-age windows, frozen
    pull counters under the partition, in-order heal drain — so
    agreement here is what licenses the 1000-worker what-ifs: the
    ``region_partition`` defaults extrapolate exactly the machinery
    this gate pinned to a live trace."""
    from distkeras_tpu.sim.cluster import LinkClass
    from distkeras_tpu.sim.scenarios import region_partition

    band = _band_pct(band_pct)
    fanouts = [int(f) for f in live["fanouts"]]
    lat = float(live.get("link_latency_s", 0.001))
    codec = str(live.get("codec", "none"))
    levels = []
    for i, fan in enumerate(fanouts):
        top = i == len(fanouts) - 1
        name = "region" if top else f"tier{i}"
        levels.append((name, fan,
                       LinkClass(name, lat, jitter=0.10,
                                 codec=codec if top else "none")))
    workers, rounds = int(live["workers"]), int(live["rounds"])
    sim = region_partition(workers=workers, seed=seed, rounds=rounds,
                           work_s=float(live["work_s"]),
                           partition=tuple(live["partition"]),
                           levels=levels,
                           flush_s=float(live["flush_s"]))
    sim_cut = (workers * rounds) / max(1, int(sim["root_commits"]))
    stale = {int(g): int(s)
             for g, s in sim["staleness_by_region"].items()}
    part = int(sim["partitioned_region"])
    sim_spike = float(stale.get(part, 0))
    live_cut = float(live["ingress_cut"])
    live_spike = float(live["staleness_spike"])
    cut_ratio = (sim_cut / live_cut) if live_cut else None
    spike_ratio = (sim_spike + 1.0) / (live_spike + 1.0)

    def _in_band(ratio: Optional[float]) -> bool:
        return ratio is not None and abs(ratio - 1.0) <= band / 100.0

    return {
        "metric": "sim_tree_vs_live_region_partition",
        "band_pct": band, "seed": seed,
        "live": {"workers": workers, "rounds": rounds,
                 "fanouts": fanouts,
                 "work_s": round(float(live["work_s"]), 4),
                 "flush_s": round(float(live["flush_s"]), 4),
                 "partition": [round(float(t), 3)
                               for t in live["partition"]],
                 "ingress_cut": round(live_cut, 3),
                 "staleness_spike": round(live_spike, 3)},
        "sim": {"ingress_cut": round(sim_cut, 3),
                "staleness_spike": round(sim_spike, 3),
                "root_commits": int(sim["root_commits"]),
                "checks_ok": bool(sim["ok"])},
        "ingress_cut_ratio": (round(cut_ratio, 4)
                              if cut_ratio is not None else None),
        "staleness_spike_ratio": (round(spike_ratio, 4)
                                  if spike_ratio is not None else None),
        "within_band": (_in_band(cut_ratio) and _in_band(spike_ratio)
                        and bool(sim["ok"])),
    }


# -- the flat->hier crossover replay ----------------------------------------

def _curve_rows(summary) -> Tuple[List[dict], str]:
    """The first config carrying a ``hier_curve``, resolved from a dict,
    a path, or the repo-root default."""
    if summary is None:
        summary = "BENCH_SUMMARY.json"
    if isinstance(summary, str):
        if not os.path.exists(summary):
            raise FileNotFoundError(f"no bench summary at {summary}")
        with open(summary, "r", encoding="utf-8") as f:
            summary = json.load(f)
    for cfg in summary.get("configs", []):
        if cfg.get("hier_curve"):
            return list(cfg["hier_curve"]), str(cfg.get("metric"))
    raise ValueError("bench summary carries no hier_curve block")


def _replay_point(workers: int, rounds: int, topology: str,
                  service_s: float, flush_cost_s: float, flush_s: float,
                  seed: int, sigma: float = 0.02) -> dict:
    """DES one curve point: ``workers`` zero-think workers against one
    serialized resource. Flat: every commit is a root visit costing
    ``service_s``. Hier: the resource is the aggregator — ``service_s``
    per commit, plus ``flush_cost_s`` whenever the real
    :class:`SimAggregator` flush policy (fan-in = W OR age > flush
    interval) trips, so root amortization emerges from the policy."""
    import math

    eng = SimEngine(seed)
    free = [0.0]
    counts = {w: 0 for w in range(workers)}
    last = [0.0]
    agg = SimAggregator("bench-agg", fan_in=workers,
                        flush_s=flush_s) if topology == "hier" else None
    root_commits = [0]
    mu = math.log(service_s)

    def arrive(w: int) -> None:
        start = max(eng.now(), free[0])
        busy = eng.lognormal(mu, sigma, cap=4.0 * service_s)
        if agg is not None:
            if agg.fold(start, 0, 1.0) is not None:
                root_commits[0] += 1
                busy += flush_cost_s
        else:
            root_commits[0] += 1
        free[0] = start + busy
        eng.at(free[0], finish, w)

    def finish(w: int) -> None:
        counts[w] += 1
        last[0] = max(last[0], eng.now())
        if counts[w] < rounds:
            arrive(w)

    for w in range(workers):
        arrive(w)
    eng.run()
    if agg is not None and agg.take(eng.now()) is not None:
        root_commits[0] += 1
    wall = last[0]
    commits = sum(counts.values())
    return {"wall_s": wall, "worker_commits": commits,
            "root_commits": root_commits[0],
            "worker_commits_per_sec": (commits / wall) if wall else None}


def hier_crossover(summary=None, band_pct: Optional[float] = None,
                   ratio_band: float = RATIO_BAND,
                   flush_s: float = 0.5, seed: int = 0) -> dict:
    """Replay the bench ``hier_curve`` through the DES; see the module
    docstring for the calibration/held-out split. Returns per-point
    predictions, held-out errors, the predicted and measured crossover
    worker counts, and the root-ingress cut at the crossover."""
    rows, metric = _curve_rows(summary)
    band = _band_pct(band_pct)
    by_key: Dict[Tuple[int, str], dict] = {
        (int(r["workers"]), str(r["topology"])): r for r in rows}

    def period(w: int, topo: str) -> float:
        # per-worker commit period; worker_commits_per_sec is fleet-total
        return w / float(by_key[(w, topo)]["worker_commits_per_sec"])

    flat1, flat2 = period(1, "flat"), period(2, "flat")
    # least squares through the origin over the calibration points for
    # the serialized-root model p(W) = W * S
    s_flat = (1 * flat1 + 2 * flat2) / (1 + 4)
    rounds = int(round(by_key[(1, "flat")]["root_commits"]))
    tokens_per_round = (float(by_key[(1, "flat")]["tokens_per_sec"])
                        / float(by_key[(1, "flat")]
                                ["worker_commits_per_sec"]))
    # hier split from the curve's endpoints: per-commit time is
    # s_agg + r * s_root where r is the flush/commit ratio the summary's
    # root-commit counts pin (r = 1 at W=1 — every commit flushes).
    hier_ws = sorted(w for (w, topo) in by_key if topo == "hier")
    w_lo, w_hi = hier_ws[0], hier_ws[-1]

    def flush_ratio(w: int) -> float:
        row = by_key[(w, "hier")]
        return float(row["root_commits"]) / max(1, rounds * w)

    p_lo = period(w_lo, "hier")
    p_hi = period(w_hi, "hier") / w_hi * 1.0  # per-commit at max W
    r_lo, r_hi = flush_ratio(w_lo), flush_ratio(w_hi)
    if w_hi > w_lo and r_lo > r_hi:
        s_root = max(0.0, (p_lo - p_hi) / (r_lo - r_hi))
    else:
        s_root = 0.0
    s_agg = p_lo - r_lo * s_root
    calibration_keys = {(1, "flat"), (2, "flat"),
                        (w_lo, "hier"), (w_hi, "hier")}

    points = []
    for (w, topo), row in sorted(by_key.items(), key=lambda kv: kv[0]):
        pred = _replay_point(w, rounds, topo,
                             s_agg if topo == "hier" else s_flat,
                             s_root, flush_s, seed)
        predicted_tps = (tokens_per_round * pred["worker_commits"]
                         / pred["wall_s"])
        measured_tps = float(row["tokens_per_sec"])
        err = abs(predicted_tps - measured_tps) / measured_tps
        points.append({
            "workers": w, "topology": topo,
            "measured_tokens_per_sec": measured_tps,
            "predicted_tokens_per_sec": round(predicted_tps, 1),
            "error_pct": round(100.0 * err, 1),
            "held_out": (w, topo) not in calibration_keys,
            "predicted_root_commits": pred["root_commits"],
            "measured_root_commits": row.get("root_commits"),
        })

    def ratios(key: str) -> Dict[int, float]:
        tps = {(p["workers"], p["topology"]): p[key] for p in points}
        return {w: tps[(w, "hier")] / tps[(w, "flat")]
                for w in sorted({p["workers"] for p in points})
                if (w, "hier") in tps and (w, "flat") in tps}

    def crossover(ratio_by_w: Dict[int, float]) -> Optional[int]:
        for w in sorted(ratio_by_w):
            if ratio_by_w[w] >= ratio_band:
                return w
        return None

    pred_ratio = ratios("predicted_tokens_per_sec")
    meas_ratio = ratios("measured_tokens_per_sec")
    pred_x, meas_x = crossover(pred_ratio), crossover(meas_ratio)

    def ingress_cut(w: Optional[int], key: str) -> Optional[float]:
        if w is None:
            return None
        by = {(p["workers"], p["topology"]): p[key] for p in points}
        hier = by.get((w, "hier"))
        return (by[(w, "flat")] / hier) if hier else None

    held_out = [p for p in points if p["held_out"]]
    return {
        "metric": metric,
        "calibration": {"service_flat_s": round(s_flat, 4),
                        "service_agg_s": round(s_agg, 4),
                        "flush_cost_s": round(s_root, 4),
                        "rounds": rounds,
                        "tokens_per_round": round(tokens_per_round, 1),
                        "flush_s": flush_s, "seed": seed},
        "points": points,
        "band_pct": band,
        "within_band": all(p["error_pct"] <= band for p in held_out),
        "max_held_out_error_pct": max(
            (p["error_pct"] for p in held_out), default=0.0),
        "ratio_band": ratio_band,
        "predicted_ratio": {str(w): round(r, 3)
                            for w, r in pred_ratio.items()},
        "measured_ratio": {str(w): round(r, 3)
                           for w, r in meas_ratio.items()},
        "predicted_crossover_workers": pred_x,
        "measured_crossover_workers": meas_x,
        "crossover_reproduced": (pred_x is not None and pred_x == meas_x),
        "predicted_ingress_cut": ingress_cut(
            pred_x, "predicted_root_commits"),
        "measured_ingress_cut": ingress_cut(
            meas_x, "measured_root_commits"),
    }
