"""Driving the REAL FleetScheduler on a virtual clock.

The scheduler's two seams (``clock=``, ``thread_factory=``) are filled
here: :class:`SimThreadFactory` builds :class:`SimThread`\\ s — cooperative
stand-ins whose ``start()`` runs the scheduler's worker body
*synchronously*. The body is the scheduler's own closure: it calls
``runtime.worker_main(wid, should_run)``, which under simulation
registers an event-driven worker with the engine and returns immediately
instead of blocking. The SimThread then stays "alive" until that worker
finishes (released, crashed, or out of work), so the scheduler's REAL
reap logic — crash-restart budgets, drain completion, grace-window
revocation — runs unmodified against zero OS threads.

:class:`SimJobRuntime` satisfies the FleetJob runtime duck-type
(``ensure_started`` / ``worker_main`` / ``progress`` / ``done`` /
``revoke`` / ``close``). Each simulated worker alternates a work interval
(trace-fitted or parametric) with a commit against a
:class:`~distkeras_tpu.sim.cluster.SimCenter` — pull counter sampled at
round start, so staleness under concurrency is emergent, not scripted.
Commit sequences persist across restarts and re-placements (the real
"PS kept warm" contract), and :meth:`SimJobRuntime.crash` can lose the
ack of an applied commit, forcing the restarted worker to retransmit and
the center's dedup to earn its exactly-once invariant.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from distkeras_tpu.sim.cluster import SimCenter


class SimThread:
    """Cooperative thread stand-in (the scheduler only ever calls
    ``start`` / ``is_alive`` / ``join``)."""

    def __init__(self, engine, target: Callable[[], None],
                 name: str = "sim"):
        self.engine = engine
        self.name = name
        self._target = target
        self._state = None     # bound by SimJobRuntime.worker_main
        self._started = False

    def start(self) -> None:
        self._started = True
        prev = self.engine.current_thread
        self.engine.current_thread = self
        try:
            self._target()
        finally:
            self.engine.current_thread = prev

    def bind(self, state) -> None:
        self._state = state

    def is_alive(self) -> bool:
        return bool(self._started and self._state is not None
                    and not self._state.finished)

    def join(self, timeout: Optional[float] = None) -> None:
        return None


class SimThreadFactory:
    """``thread_factory=`` seam filler: engine-bound, Thread-signature
    compatible (extra kwargs like ``daemon`` are accepted and ignored)."""

    def __init__(self, engine):
        self.engine = engine
        self.created = 0

    def __call__(self, target=None, name: str = "sim", **_kw) -> SimThread:
        self.created += 1
        return SimThread(self.engine, target, name=name)


class _WorkerState:
    """One granted worker's live half (a fresh one per (re)spawn — stale
    scheduled events hold the old object and no-op on ``finished``)."""

    __slots__ = ("wid", "should_run", "thread", "pulled", "finished",
                 "revoked")

    def __init__(self, wid: int, should_run, thread):
        self.wid = wid
        self.should_run = should_run
        self.thread = thread
        self.pulled = None
        self.finished = False
        self.revoked = False


class SimJobRuntime:
    """Simulated job runtime; see the module docstring.

    ``round_time`` is ``(engine, wid) -> seconds`` (the work+commit
    interval); ``rounds_target`` is the job's total applied-commit goal
    across all workers. ``commit_value`` is the per-commit delta folded
    into the center (1.0 makes the center value a commit counter)."""

    def __init__(self, engine, name: str,
                 round_time: Callable[[object, int], float],
                 rounds_target: int,
                 center: Optional[SimCenter] = None,
                 commit_value: float = 1.0,
                 start_jitter_s: float = 0.05,
                 worker_slots: Optional[int] = None):
        self.engine = engine
        self.name = name
        self.round_time = round_time
        self.rounds_target = int(rounds_target)
        self.center = center if center is not None else SimCenter()
        self.commit_value = float(commit_value)
        self.start_jitter_s = float(start_jitter_s)
        if worker_slots is not None:
            #: optional data-layout bound (the scheduler checks it).
            self.worker_slots = int(worker_slots)
        self.endpoint = f"sim://{name}"
        self.rounds_done = 0
        self.started = False
        self.closed = False
        self.crashes = 0
        self.resends_expected = 0
        self._next_seq: Dict[int, int] = {}
        self._workers: Dict[int, _WorkerState] = {}
        #: per-tick-sampled worker counts (scenarios derive shrink/expand
        #: thrash from the direction changes of this series).
        self.granted_series: list = []

    # -- the FleetJob runtime protocol ---------------------------------

    def ensure_started(self) -> None:
        self.started = True

    def worker_main(self, worker_id: int, should_run) -> None:
        thread = self.engine.current_thread
        if thread is None:
            raise RuntimeError(
                "SimJobRuntime.worker_main outside a SimThread — pass "
                "thread_factory=SimThreadFactory(engine) to the scheduler")
        st = _WorkerState(worker_id, should_run, thread)
        thread.bind(st)
        self._workers[worker_id] = st
        jitter = (self.engine.rng.uniform(0.0, self.start_jitter_s)
                  if self.start_jitter_s > 0 else 0.0)
        self.engine.after(jitter, self._begin_round, st)

    def progress(self) -> int:
        return self.rounds_done

    def done(self) -> bool:
        return self.rounds_done >= self.rounds_target

    def revoke(self, worker_id: int) -> None:
        st = self._workers.get(worker_id)
        if st is not None:
            st.revoked = True

    def close(self) -> None:
        self.closed = True

    # -- the event-driven worker loop ----------------------------------

    def _finished(self, st: _WorkerState) -> bool:
        if st.finished:
            return True
        if (self.closed or self.done() or st.revoked
                or not st.should_run()):
            st.finished = True
            return True
        return False

    def _begin_round(self, st: _WorkerState) -> None:
        if self._finished(st):
            return
        st.pulled = self.center.pull()
        self.engine.after(self.round_time(self.engine, st.wid),
                          self._end_round, st)

    def _end_round(self, st: _WorkerState) -> None:
        if self._finished(st):
            return
        wid = st.wid
        seq = self._next_seq.get(wid, 0)
        res = self.center.commit(wid, seq, st.pulled, self.commit_value)
        self._next_seq[wid] = seq + 1
        if res["applied"]:
            # a lose_ack retransmit is deduped by the center and must not
            # double-count progress
            self.rounds_done += 1
        self._begin_round(st)

    # -- fault injection (scenario-controlled) -------------------------

    def crash(self, worker_id: int, lose_ack: bool = False) -> bool:
        """Kill one worker's stand-in thread mid-flight (the scheduler's
        reaper sees a dead, unreleased, unfinished worker — a crash —
        and spends restart budget on it). With ``lose_ack``, the last
        applied commit's ack is lost: the restarted worker re-sends that
        sequence and the center's dedup must absorb the duplicate."""
        st = self._workers.get(worker_id)
        if st is None or st.finished:
            return False
        st.finished = True
        self.crashes += 1
        if lose_ack and self._next_seq.get(worker_id, 0) > 0:
            self._next_seq[worker_id] -= 1
            self.resends_expected += 1
        return True

    def active_workers(self) -> int:
        return sum(1 for st in self._workers.values() if not st.finished)
