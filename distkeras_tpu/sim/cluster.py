"""Simulated data plane built on the REAL counter rules.

:class:`SimCenter` is the simulator's parameter-server stand-in. "Stand-
in" covers the transport only — the semantics are the production ones,
imported, not imitated:

* staleness comes from :func:`distkeras_tpu.netps.fold.counter_staleness`
  (the server's update counter minus the committer's pull-time counter,
  per-shard tuples reduced by the MIN rule) — the exact function
  ``PSServer._fold_locked`` calls;
* every applied commit goes through the real
  :func:`~distkeras_tpu.netps.fold.fold_delta` on a one-float center, so
  discipline scaling (DynSGD's ``1/(staleness+1)``) is the production
  arithmetic, and the center value doubles as an exactly-once witness:
  for downpour, ``center == applied_commits * delta`` to the last bit —
  a duplicate that slipped past dedup would show up as a fold;
* per-wid ``last_seq`` dedup and the ``commit_log`` mirror the server's
  exactly-once bookkeeping; :meth:`SimCenter.promote` is a failover
  (epoch bump, dedup state carried — the standby's guarantee).

:class:`SimAggregator` mirrors the hier aggregator's fold-side rules
(``netps.hier.AggregatorServer._fold_locked``): accumulate deltas,
forward the MIN of the folded commits' pull counters (staleness can only
be overstated), flush upstream on fan-in or age. :class:`TreeTopology`
wires N levels of them (host -> pool -> region -> root) with per-link
latency/codec classes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu.netps.fold import (
    check_discipline,
    counter_scalar,
    counter_staleness,
    fold_delta,
)


class SimCenter:
    """One (possibly sharded) center; see the module docstring."""

    def __init__(self, discipline: str = "downpour", shards: int = 1):
        self.discipline = check_discipline(discipline)
        self.shards = max(1, int(shards))
        self._center = [np.zeros(1, np.float32)]
        self._updates = [0] * self.shards
        self._last_seq: Dict[int, int] = {}
        self.epoch = 0
        self.epoch_history: List[int] = [0]
        self.commit_log: List[Tuple[int, int, int]] = []
        self.commits_total = 0
        self.duplicates = 0
        self.max_staleness = 0

    def pull(self):
        """The pull-time counter a committer carries: per-shard tuple for
        a sharded center (the MIN rule reduces it at fold time), plain
        int otherwise."""
        if self.shards > 1:
            return tuple(self._updates)
        return self._updates[0]

    def updates(self):
        return self.pull()

    def commit(self, wid: int, seq: int, pulled, value: float = 1.0) -> dict:
        """One commit: real dedup, real staleness rule, real fold."""
        if seq <= self._last_seq.get(wid, -1):
            self.duplicates += 1
            return {"applied": False, "duplicate": True, "staleness": None}
        staleness = counter_staleness(
            self._updates if self.shards > 1 else self._updates[0], pulled)
        fold_delta(self._center,
                   [np.full(1, value, np.float32)],
                   self.discipline, staleness)
        self._last_seq[wid] = seq
        for i in range(self.shards):
            self._updates[i] += 1
        self.commit_log.append((wid, seq, staleness))
        self.commits_total += 1
        self.max_staleness = max(self.max_staleness, staleness)
        return {"applied": True, "duplicate": False, "staleness": staleness}

    def promote(self) -> int:
        """Failover: the standby takes over — epoch bumps (fencing), the
        dedup map and counters carry (replication keeps them warm)."""
        self.epoch += 1
        self.epoch_history.append(self.epoch)
        return self.epoch

    def center_value(self) -> float:
        return float(self._center[0][0])

    def distinct_commits(self) -> int:
        return len({(w, s) for w, s, _st in self.commit_log})

    def exactly_once(self) -> bool:
        """The invariant every scenario asserts: applied == distinct
        (wid, seq) — nothing double-folded, nothing silently dropped."""
        return self.commits_total == self.distinct_commits()


class LinkClass:
    """One link tier of the aggregation tree: a base one-way latency, a
    lognormal jitter (sigma in log space), and a codec class whose
    per-hop encode/decode cost rides the latency. Sampled from the
    engine RNG — deterministic under a seed."""

    #: codec -> per-hop transform cost factor over the base latency
    #: (none: raw f32; bf16: truncate-only; int8: quantize + scale).
    CODEC_COST = {"none": 0.0, "bf16": 0.10, "int8": 0.25}

    def __init__(self, name: str, latency_s: float, jitter: float = 0.10,
                 codec: str = "none"):
        if codec not in self.CODEC_COST:
            raise ValueError(f"unknown codec {codec!r} for link {name!r}")
        self.name = name
        self.latency_s = float(latency_s)
        self.jitter = float(jitter)
        self.codec = codec
        #: partition windows: (t0, t1) intervals during which the link
        #: blackholes traffic (scenario-controlled).
        self.partitions: List[Tuple[float, float]] = []

    def sample(self, engine) -> float:
        import math

        base = self.latency_s * (1.0 + self.CODEC_COST[self.codec])
        if self.jitter <= 0.0 or base <= 0.0:
            return base
        return engine.lognormal(math.log(base), self.jitter,
                                cap=10.0 * base)

    def partition(self, t0: float, t1: float) -> None:
        self.partitions.append((float(t0), float(t1)))

    def is_down(self, t: float) -> bool:
        return any(a <= t < b for a, b in self.partitions)

    def heals_at(self, t: float) -> float:
        """The end of the partition window covering ``t`` (or ``t``)."""
        for a, b in self.partitions:
            if a <= t < b:
                return b
        return t


class SimAggregator:
    """One aggregation-tree node: the hier fold-side rules."""

    def __init__(self, name: str, fan_in: int, flush_s: float,
                 uplink: Optional[LinkClass] = None):
        self.name = name
        self.fan_in = max(1, int(fan_in))
        self.flush_s = float(flush_s)
        self.uplink = uplink
        self._acc_value = 0.0
        self._acc_pulled: Optional[int] = None
        self._acc_count = 0
        self._acc_t0: Optional[float] = None
        self.flushes = 0

    def fold(self, t: float, pulled, value: float) -> Optional[dict]:
        """Absorb one downstream commit; returns a flush payload when the
        flush policy (fan-in reached OR age > flush interval — the real
        ``_take_acc_locked`` policy) trips at this arrival."""
        pulled = counter_scalar(pulled)
        self._acc_value += value
        self._acc_count += 1
        # The hier MIN rule: the forwarded pull counter is the MIN over
        # the folded commits' counters — overstating staleness is safe,
        # understating would let DynSGD under-discount.
        self._acc_pulled = (pulled if self._acc_pulled is None
                            else min(self._acc_pulled, pulled))
        if self._acc_t0 is None:
            self._acc_t0 = t
        if (self._acc_count >= self.fan_in
                or t - self._acc_t0 >= self.flush_s):
            return self.take(t)
        return None

    def take(self, t: float) -> Optional[dict]:
        """Drain the accumulation as one upstream commit payload."""
        if self._acc_count == 0:
            return None
        out = {"value": self._acc_value, "pulled": self._acc_pulled,
               "count": self._acc_count, "t": t}
        self._acc_value, self._acc_pulled = 0.0, None
        self._acc_count, self._acc_t0 = 0, None
        self.flushes += 1
        return out

    def pending(self) -> int:
        return self._acc_count


class TreeTopology:
    """An N-level aggregation tree over ``workers`` leaves.

    ``levels`` is a bottom-up spec ``[(name, fanout, LinkClass), ...]``
    — e.g. host (fanout 8) -> pool (fanout 4) -> region (fanout N) —
    with the last level's uplink feeding the root center. Workers are
    assigned to leaf groups contiguously, so worker w's path is derived,
    not stored: level-k group index is ``w // prod(fanouts[:k+1])``."""

    def __init__(self, workers: int,
                 levels: Sequence[Tuple[str, int, LinkClass]],
                 flush_s: float = 0.02):
        self.workers = int(workers)
        self.levels = list(levels)
        self.flush_s = float(flush_s)
        self.aggregators: List[Dict[int, SimAggregator]] = []
        self._partitions: Dict[Tuple[int, int],
                               List[Tuple[float, float]]] = {}
        group = self.workers
        stride = 1
        for name, fanout, link in self.levels:
            stride *= int(fanout)
            group = (self.workers + stride - 1) // stride
            tier = {}
            for g in range(group):
                tier[g] = SimAggregator(
                    f"{name}-{g}", fan_in=int(fanout),
                    flush_s=self.flush_s, uplink=link)
            self.aggregators.append(tier)

    def partition(self, level: int, group: int, t0: float,
                  t1: float) -> None:
        """Black-hole one group's uplink at ``level`` for ``[t0, t1)``.

        LinkClass objects are shared per level (they model the link
        *tier*), so partitions are keyed here per (level, group)."""
        self._partitions.setdefault((int(level), int(group)), []).append(
            (float(t0), float(t1)))

    def link_down(self, level: int, group: int, t: float) -> bool:
        return any(a <= t < b for a, b in
                   self._partitions.get((int(level), int(group)), ()))

    def heals_at(self, level: int, group: int, t: float) -> float:
        """End of the partition window covering ``t`` (or ``t``)."""
        for a, b in self._partitions.get((int(level), int(group)), ()):
            if a <= t < b:
                return b
        return t

    def group_of(self, worker: int, level: int) -> int:
        stride = 1
        for _name, fanout, _link in self.levels[:level + 1]:
            stride *= int(fanout)
        return worker // stride

    def path(self, worker: int) -> List[SimAggregator]:
        """The worker's aggregator chain, leaf-most first."""
        return [self.aggregators[lvl][self.group_of(worker, lvl)]
                for lvl in range(len(self.levels))]

    def level_links(self, level: int) -> LinkClass:
        return self.levels[level][2]
