"""Trace-calibrated fleet simulation: the what-if plane.

This box can never run a thousand-worker fleet live, but ROADMAP's
planet-scale item does not actually need the hardware — it needs the
*control logic* exercised at that scale. PR 14's collector-merged
critical-path segments (encode/wire/queue/fold/fsync/replicate/ack
p50/p99 per deployment) ARE a timing model; this package builds the
deterministic discrete-event simulator they calibrate, and points it at
the REAL code wherever behavior could regress:

* the actual :class:`~distkeras_tpu.fleet.scheduler.FleetScheduler`,
  ticked on a virtual clock with cooperative stand-in threads — real
  quota/gang/preemption/floor/restart logic, simulated job runtimes;
* the actual SLO engine, alert manager, and sentinels, fed synthesized
  :class:`~distkeras_tpu.telemetry.health.hub.MetricsHub` series through
  its ``feed()`` seam — real burn-rate and hysteresis math;
* the real staleness-counter rules (``netps.fold.counter_staleness``,
  the hier MIN reduction, per-wid dedup, ``fold_delta`` arithmetic on a
  one-float center) inside :class:`~distkeras_tpu.sim.cluster.SimCenter`.

Layout: :mod:`~distkeras_tpu.sim.core` (the seedable event engine),
:mod:`~distkeras_tpu.sim.model` (trace-fitted latency model over
``tracing.analysis.segment_model``), :mod:`~distkeras_tpu.sim.cluster`
(centers, aggregation trees, link classes),
:mod:`~distkeras_tpu.sim.fleet_driver` (the scheduler seams),
:mod:`~distkeras_tpu.sim.calibrate` (bench replay + the flat→hier
crossover gate), :mod:`~distkeras_tpu.sim.scenarios` (preemption storms,
failover cascades, region partitions, alert storms), and the
``python -m distkeras_tpu.sim`` CLI (``run`` / ``calibrate`` /
``report``). Protocol and guarantees: docs/SIMULATION.md.
"""

from distkeras_tpu.sim.calibrate import hier_crossover, sim_drift
from distkeras_tpu.sim.cluster import (
    LinkClass,
    SimAggregator,
    SimCenter,
    TreeTopology,
)
from distkeras_tpu.sim.core import SimEngine
from distkeras_tpu.sim.fleet_driver import SimJobRuntime, SimThreadFactory
from distkeras_tpu.sim.model import TimingModel
from distkeras_tpu.sim.scenarios import SCENARIOS, run_scenario

__all__ = [
    "LinkClass",
    "SCENARIOS",
    "SimAggregator",
    "SimCenter",
    "SimEngine",
    "SimJobRuntime",
    "SimThreadFactory",
    "TimingModel",
    "TreeTopology",
    "hier_crossover",
    "run_scenario",
    "sim_drift",
]
