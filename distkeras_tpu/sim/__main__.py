"""``python -m distkeras_tpu.sim`` — run scenarios, calibrate, report.

Subcommands::

    run <scenario> [--seed N] [--workers N] [--json]
        Run one what-if scenario (see ``--list``); exits non-zero when
        any of the scenario's invariant checks fails — the CI
        ``sim-regression`` job is three of these plus ``calibrate``.

    calibrate [--summary PATH] [--band PCT] [--seed N] [--json]
        The flat->hier crossover replay against the bench summary's
        ``hier_curve``: held-out predictions must land within the band
        and the predicted crossover must match the measured one.
        ``--tree-live live.json`` runs the aggregation-tree gate
        instead: re-fit ``region_partition`` from a recorded live tree
        run and assert the root ingress cut and partition staleness
        spike agree within the band (the ``tree_parity`` block the tree
        chaos smoke writes into BENCH_SUMMARY.json).

    report --trace-dir DIR [--json]
        Fit the timing model from a trace stream and print it (the same
        ``segment_model`` numbers the telemetry ``--trace`` report's
        Calibration section renders, plus the work pseudo-segment).
"""

from __future__ import annotations

import argparse
import json
import sys

from distkeras_tpu.sim.scenarios import SCENARIOS, run_scenario


def _render_checks(out: dict) -> str:
    lines = [f"scenario: {out.get('scenario')}  seed={out.get('seed')}  "
             f"virtual={out.get('virtual_s', '-')}s  "
             f"events={out.get('events', '-')}"]
    for name, ok in (out.get("checks") or {}).items():
        lines.append(f"  [{'PASS' if ok else 'FAIL'}] {name}")
    lines.append("OK" if out.get("ok") else "FAILED")
    return "\n".join(lines)


def _cmd_run(args: argparse.Namespace) -> int:
    kwargs = {}
    if args.seed is not None:
        kwargs["seed"] = args.seed
    if args.workers is not None:
        kwargs["workers"] = args.workers
    out = run_scenario(args.scenario, **kwargs)
    print(json.dumps(out, indent=2, sort_keys=True) if args.json
          else _render_checks(out))
    return 0 if out.get("ok") else 1


def _cmd_calibrate(args: argparse.Namespace) -> int:
    from distkeras_tpu.sim.calibrate import hier_crossover, tree_parity

    if args.tree_live:
        with open(args.tree_live, "r", encoding="utf-8") as f:
            live = json.load(f)
        out = tree_parity(live, band_pct=args.band, seed=args.seed or 0)
        if args.json:
            print(json.dumps(out, indent=2, sort_keys=True))
        else:
            print(f"tree parity: ingress cut live="
                  f"{out['live']['ingress_cut']} sim="
                  f"{out['sim']['ingress_cut']} "
                  f"(ratio {out['ingress_cut_ratio']})  staleness spike "
                  f"live={out['live']['staleness_spike']} sim="
                  f"{out['sim']['staleness_spike']} "
                  f"(ratio {out['staleness_spike_ratio']})  band "
                  f"{out['band_pct']:.0f}%")
        print("OK" if out["within_band"] else "FAILED")
        return 0 if out["within_band"] else 1
    out = hier_crossover(summary=args.summary, band_pct=args.band,
                         seed=args.seed or 0)
    if args.json:
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(f"calibration: S_flat="
              f"{out['calibration']['service_flat_s'] * 1e3:.1f}ms  "
              f"S_agg={out['calibration']['service_agg_s'] * 1e3:.1f}ms  "
              f"flush={out['calibration']['flush_cost_s'] * 1e3:.1f}ms")
        for p in out["points"]:
            tag = "held-out" if p["held_out"] else "calibrated"
            print(f"  W={p['workers']} {p['topology']:<4} "
                  f"measured={p['measured_tokens_per_sec']:9.1f} "
                  f"predicted={p['predicted_tokens_per_sec']:9.1f} "
                  f"err={p['error_pct']:4.1f}%  ({tag})")
        print(f"held-out max err {out['max_held_out_error_pct']:.1f}% "
              f"(band {out['band_pct']:.0f}%)  crossover: predicted "
              f"W={out['predicted_crossover_workers']} measured "
              f"W={out['measured_crossover_workers']}")
    ok = out["within_band"] and out["crossover_reproduced"]
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


def _cmd_report(args: argparse.Namespace) -> int:
    from distkeras_tpu.sim.model import TimingModel

    model = TimingModel.from_dir(args.trace_dir)
    desc = model.describe()
    if args.json:
        print(json.dumps(desc, indent=2, sort_keys=True))
        return 0
    print(f"timing model: {desc['commits']} commit path(s)")
    rows = dict(desc["segments"])
    if "work" in desc:
        rows["work"] = desc["work"]
    for name, d in rows.items():
        fit = (f"lognorm(mu={d['lognorm_mu']:.3f}, "
               f"sigma={d['lognorm_sigma']:.3f})" if d["fit_ok"]
               else "mean replay (too few samples)")
        print(f"  {name:<10} n={d['count']:<6} "
              f"mean={d['mean_s'] * 1e3:8.3f}ms "
              f"p99={d['p99_s'] * 1e3:8.3f}ms  {fit}")
    for w in desc["warnings"]:
        print(f"  WARNING: {w}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.sim",
        description="trace-calibrated fleet simulator")
    sub = ap.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="run one what-if scenario")
    runp.add_argument("scenario", choices=sorted(SCENARIOS))
    runp.add_argument("--seed", type=int, default=None)
    runp.add_argument("--workers", type=int, default=None)
    runp.add_argument("--json", action="store_true")

    calp = sub.add_parser("calibrate",
                          help="bench hier_curve replay gate")
    calp.add_argument("--summary", default=None,
                      help="BENCH_SUMMARY.json path (default: repo root)")
    calp.add_argument("--band", type=float, default=None,
                      help="tolerance pct (default DKTPU_SIM_BAND_PCT)")
    calp.add_argument("--seed", type=int, default=None)
    calp.add_argument("--tree-live", default=None, metavar="PATH",
                      help="recorded live-tree run (JSON dict): run the "
                           "tree_parity gate instead of the hier replay")
    calp.add_argument("--json", action="store_true")

    repp = sub.add_parser("report", help="fitted timing model from traces")
    repp.add_argument("--trace-dir", required=True)
    repp.add_argument("--json", action="store_true")

    args = ap.parse_args(argv)
    if args.cmd == "run":
        return _cmd_run(args)
    if args.cmd == "calibrate":
        return _cmd_calibrate(args)
    return _cmd_report(args)


if __name__ == "__main__":
    sys.exit(main())
