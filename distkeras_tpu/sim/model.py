"""The trace-calibrated timing model.

Calibration is a replay of evidence, not a guess: the per-segment latency
distributions come from :func:`distkeras_tpu.telemetry.tracing.analysis.
segment_model` over a collector-merged trace stream — the SAME extraction
the ``--trace`` report renders, so the simulator and the report can never
disagree about what was measured. On top of the lifecycle segments this
module extracts one pseudo-segment the traces imply but never name:
**work**, the per-worker gap between consecutive commit roots minus the
commit's own end-to-end time — the compute+pull interval a simulated
worker spends between commits.

Sampling: a fitted segment draws from its lognormal (log-space moment
fit), capped at 4x the observed max so a thin tail cannot schedule an
outlier the deployment never produced; a segment too thin to fit
(``fit_ok`` False) replays its mean. All draws go through the engine RNG.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from distkeras_tpu.telemetry.tracing import analysis

#: lifecycle segments that block the committing worker (``replicate`` is
#: the standby's async pull — off the commit's critical path).
BLOCKING_SEGMENTS = ("encode", "wire", "queue", "fold", "fsync", "ack")
#: cap factor over the observed max for fitted-tail draws.
TAIL_CAP = 4.0


class SegmentDist:
    """One segment's fitted distribution + provenance counts."""

    __slots__ = ("name", "count", "mean_s", "p50_s", "p99_s", "max_s",
                 "mu", "sigma", "fit_ok")

    def __init__(self, name: str, count: int, mean_s: float, p50_s: float,
                 p99_s: float, max_s: float, mu: Optional[float] = None,
                 sigma: Optional[float] = None, fit_ok: bool = False):
        self.name = name
        self.count = int(count)
        self.mean_s = float(mean_s)
        self.p50_s = float(p50_s)
        self.p99_s = float(p99_s)
        self.max_s = float(max_s)
        self.mu = mu
        self.sigma = sigma
        self.fit_ok = bool(fit_ok)

    @classmethod
    def from_info(cls, name: str, info: dict) -> "SegmentDist":
        """From one :func:`segment_model` segment entry."""
        fit = info.get("lognorm") or {}
        return cls(name, info["count"], info["mean_s"], info["p50_s"],
                   info["p99_s"], info["max_s"], fit.get("mu"),
                   fit.get("sigma"), info.get("fit_ok", False))

    @classmethod
    def fixed(cls, name: str, value_s: float) -> "SegmentDist":
        """A degenerate (constant) segment for parametric scenarios."""
        return cls(name, 0, value_s, value_s, value_s, value_s)

    def sample(self, engine) -> float:
        if self.fit_ok and self.mu is not None:
            return engine.lognormal(self.mu, self.sigma,
                                    cap=TAIL_CAP * self.max_s)
        return self.mean_s

    def describe(self) -> dict:
        return {"count": self.count, "mean_s": self.mean_s,
                "p50_s": self.p50_s, "p99_s": self.p99_s,
                "max_s": self.max_s, "lognorm_mu": self.mu,
                "lognorm_sigma": self.sigma, "fit_ok": self.fit_ok}


def _work_gaps(commits: list) -> list:
    """Per-worker inter-commit gaps: for each wid's commit roots in t0
    order, ``gap_i = t0[i+1] - (t0[i] + e2e[i])`` clamped at zero — the
    compute+pull time between one commit's ack and the next commit."""
    by_wid: Dict[object, list] = {}
    for _tid, root, _durs, e2e in commits:
        wid = root.get("wid")
        if wid is None:
            continue
        by_wid.setdefault(wid, []).append(
            (float(root.get("t0") or 0.0), e2e))
    gaps = []
    for seq in by_wid.values():
        seq.sort()
        for (t0, e2e), (t1, _next) in zip(seq, seq[1:]):
            gaps.append(max(0.0, t1 - (t0 + e2e)))
    return gaps


class TimingModel:
    """Fitted segment distributions + the work pseudo-segment."""

    def __init__(self, segments: Dict[str, SegmentDist],
                 work: Optional[SegmentDist], commits: int,
                 warnings: Iterable[str] = ()):
        self.segments = dict(segments)
        self.work = work
        self.commits = int(commits)
        self.warnings = list(warnings)

    @classmethod
    def from_records(cls, records: list,
                     min_samples: Optional[int] = None) -> "TimingModel":
        kw = {} if min_samples is None else {"min_samples": min_samples}
        commits = analysis.commit_paths(records)
        model = analysis.segment_model(commits=commits, **kw)
        segments = {seg: SegmentDist.from_info(seg, info)
                    for seg, info in model["segments"].items()}
        gaps = sorted(_work_gaps(commits))
        work = None
        warnings = list(model["warnings"])
        if gaps:
            fit = analysis._lognorm_fit(gaps)
            info = {"count": len(gaps), "mean_s": sum(gaps) / len(gaps),
                    "p50_s": analysis._quantile(gaps, 0.50),
                    "p99_s": analysis._quantile(gaps, 0.99),
                    "max_s": gaps[-1], "lognorm": fit,
                    "fit_ok": bool(fit and fit["samples"]
                                   >= model["min_samples"])}
            work = SegmentDist.from_info("work", info)
            if not work.fit_ok:
                warnings.append(
                    f"work gaps: {len(gaps)} sample(s) too thin to fit — "
                    "replaying the mean")
        return cls(segments, work, model["commits"], warnings)

    @classmethod
    def from_dir(cls, trace_dir: str,
                 min_samples: Optional[int] = None) -> "TimingModel":
        from distkeras_tpu.telemetry.tracing.collector import (
            TelemetryCollector)

        records = TelemetryCollector.from_dir(trace_dir).records()
        return cls.from_records(records, min_samples=min_samples)

    def sample_segment(self, name: str, engine) -> float:
        dist = self.segments.get(name)
        return dist.sample(engine) if dist is not None else 0.0

    def sample_commit_client(self, engine) -> float:
        """The worker-side pre-server part of a commit: encode + wire."""
        return (self.sample_segment("encode", engine)
                + self.sample_segment("wire", engine))

    def sample_service(self, engine) -> float:
        """The serialized server-side part (the fold lock's critical
        section): fold + fsync. Queue time is NOT sampled — queueing
        emerges from contention on the simulated server resource; the
        measured ``queue`` segment stays as validation evidence."""
        return (self.sample_segment("fold", engine)
                + self.sample_segment("fsync", engine))

    def sample_ack(self, engine) -> float:
        return self.sample_segment("ack", engine)

    def sample_work(self, engine) -> float:
        return self.work.sample(engine) if self.work is not None else 0.0

    def describe(self) -> dict:
        out = {"commits": self.commits, "warnings": list(self.warnings),
               "segments": {name: d.describe()
                            for name, d in sorted(self.segments.items())}}
        if self.work is not None:
            out["work"] = self.work.describe()
        return out
