"""Structured training metrics + profiling hooks.

SURVEY.md §5: the reference records wall-clock only (``Trainer.record_training_start/
stop``) with print-level logging. Here every fold round can emit a JSONL record
(loss, samples/sec/chip, scaling efficiency inputs) and any span can be wrapped in a
``jax.profiler`` trace for Perfetto/XProf.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Optional

import jax
import numpy as np


class MetricsLogger:
    """Per-round JSONL metrics writer with throughput accounting.

    Use as the ``on_round`` callback of an engine run::

        logger = MetricsLogger("run.jsonl", samples_per_round=W*K*B, num_chips=W)
        engine.run(plan, on_round=logger)
    """

    def __init__(
        self,
        path: Optional[str] = None,
        samples_per_round: int = 0,
        num_chips: int = 1,
        extra: Optional[dict] = None,
    ):
        self.path = path
        self.samples_per_round = samples_per_round
        self.num_chips = num_chips
        self.extra = extra or {}
        self.records: list[dict] = []
        self._file = open(path, "a") if path else None
        self._last_t = time.perf_counter()

    def __call__(self, round_idx: int, loss) -> None:
        now = time.perf_counter()
        dt = now - self._last_t
        self._last_t = now
        loss = np.asarray(loss)
        rec = {
            "ts": time.time(),
            "round": round_idx,
            "loss": float(loss.mean()),
            "round_seconds": round(dt, 6),
            **self.extra,
        }
        if loss.size > 1:  # async engines report one loss per worker
            rec["worker_loss"] = [round(float(v), 6) for v in loss.ravel()]
        if self.samples_per_round and dt > 0:
            rec["samples_per_sec"] = round(self.samples_per_round / dt, 2)
            rec["samples_per_sec_per_chip"] = round(
                self.samples_per_round / dt / self.num_chips, 2
            )
        self.records.append(rec)
        if self._file:
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None

    #: callbacks arriving within this window of their predecessor are part
    #: of the same dispatch burst (blocked/auto execution delivers one
    #: callback burst per compiled block; burst-tail callbacks arrive in
    #: ~microseconds, while a real round includes at least a JSONL write)
    _BURST_EPS_S = 1e-4

    def mean_throughput(self, skip: int = 1) -> float:
        """Aggregate samples/sec, skipping the first ``skip`` timing
        segments (compile/warmup). Blocked and auto execution deliver
        callbacks in per-block bursts — a burst's first record absorbs the
        whole block's duration and the rest read ~0 s — so records are
        grouped into segments (a timing boundary plus its burst tail) and
        throughput is computed from segment totals: per-round rates or raw
        record sums would misattribute samples across block boundaries."""
        segments = []  # (rounds_in_segment, segment_seconds)
        for r in self.records:
            if "samples_per_sec" not in r:
                continue
            if segments and r["round_seconds"] < self._BURST_EPS_S:
                segments[-1][0] += 1
                segments[-1][1] += r["round_seconds"]  # conserve tail time
            else:
                segments.append([1, r["round_seconds"]])
        if len(segments) > skip:
            segments = segments[skip:]
        # else: everything landed in <= skip segments (e.g. one giant block)
        # — report over what exists rather than a meaningless 0.
        total_t = sum(t for _, t in segments)
        total_rounds = sum(n for n, _ in segments)
        if not segments or total_t <= 0:
            return 0.0
        return self.samples_per_round * total_rounds / total_t


def scaling_efficiency(sps_n: float, sps_1: float, n_chips: int) -> float:
    """BASELINE.md's headline metric: throughput(N) / (N * throughput(1))."""
    if sps_1 <= 0 or n_chips <= 0:
        return 0.0
    return sps_n / (n_chips * sps_1)


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """``jax.profiler`` span -> Perfetto/XProf trace in ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
