"""Structured training metrics + profiling hooks.

SURVEY.md §5: the reference records wall-clock only (``Trainer.record_training_start/
stop``) with print-level logging. Here every fold round can emit a JSONL record
(loss, samples/sec/chip, scaling efficiency inputs) and any span can be wrapped in a
``jax.profiler`` trace for Perfetto/XProf.

``MetricsLogger`` is a client of the unified telemetry layer
(``distkeras_tpu/telemetry/``): every round also feeds the ambient registry's
``round_seconds`` histogram and loss gauge, an attached
:class:`~distkeras_tpu.telemetry.training.DisciplineMonitor` augments records
with staleness/divergence/straggler fields, and ``close()`` appends the
registry's aggregate summary to the JSONL — so one file feeds
``python -m distkeras_tpu.telemetry report`` with rounds AND phases.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Optional

import jax
import numpy as np


class MetricsLogger:
    """Per-round JSONL metrics writer with throughput accounting.

    Use as the ``on_round`` callback of an engine run — as a context manager,
    so the file handle can't leak when the run raises::

        with MetricsLogger("run.jsonl", samples_per_round=W*K*B,
                           num_chips=W) as logger:
            engine.run(plan, on_round=logger)
    """

    def __init__(
        self,
        path: Optional[str] = None,
        samples_per_round: int = 0,
        num_chips: int = 1,
        extra: Optional[dict] = None,
        monitor=None,
        telemetry=None,
    ):
        from distkeras_tpu import telemetry as _telemetry

        self.path = path
        self.samples_per_round = samples_per_round
        self.num_chips = num_chips
        self.extra = extra or {}
        #: optional DisciplineMonitor: staleness/divergence/straggler fields
        #: per round (telemetry/training.py).
        self.monitor = monitor
        self.telemetry = telemetry if telemetry is not None else _telemetry.get()
        self.records: list[dict] = []
        #: registry window start: close() dumps only THIS run's activity
        #: (sequential runs share the process-global registry; a full dump
        #: would re-attribute the previous run's counters and spans).
        self._mark = self.telemetry.mark()
        self._file = open(path, "a") if path else None
        self._last_t = time.perf_counter()
        #: burst tracking (see __call__): the run's first callback is always
        #: a timing boundary.
        self._prev_had_state = True

    #: default for ``state``: distinguishes "caller passed nothing" (assume
    #: every call is a real timing boundary — standalone use) from an
    #: explicit ``None`` (the engine contract: blocked/auto runs hand
    #: interior rounds of a compiled block ``state=None``; only the burst's
    #: FINAL call carries a state).
    _UNSET = object()

    def __call__(self, round_idx: int, loss, state=_UNSET) -> None:
        now = time.perf_counter()
        dt = now - self._last_t
        self._last_t = now
        # Authoritative burst-tail signal, NOT a dt threshold: on slow hosts
        # a burst-tail callback still pays the previous record's JSONL write
        # (~0.2 ms), which can exceed any fixed epsilon and would poison the
        # straggler median / throughput segments. Attribution: a burst's
        # callbacks fire back-to-back AFTER the block retires, so the whole
        # block's wall time lands in the FIRST callback's dt — while the
        # state rides the LAST. A record is therefore a timing boundary iff
        # the PREVIOUS call carried a state (it closed the previous burst);
        # marking state-bearing records themselves as boundaries would
        # anchor the straggler median on JSONL-write jitter and hide every
        # genuinely slow block.
        is_tail = not self._prev_had_state
        self._prev_had_state = state is not None  # _UNSET counts as a state
        loss = np.asarray(loss)
        rec = {
            "ts": time.time(),
            "round": round_idx,
            "loss": float(loss.mean()),
            "round_seconds": round(dt, 6),
            **self.extra,
        }
        # Written on EVERY record (not just tails): an explicit False lets
        # readers classify a sub-100µs genuine boundary (in-memory logger on
        # a fast per-round engine) correctly instead of falling back to the
        # dt threshold.
        rec["burst_tail"] = is_tail
        if loss.size > 1:  # async engines report one loss per worker
            rec["worker_loss"] = [round(float(v), 6) for v in loss.ravel()]
        if self.samples_per_round and dt > 0:
            rec["samples_per_sec"] = round(self.samples_per_round / dt, 2)
            rec["samples_per_sec_per_chip"] = round(
                self.samples_per_round / dt / self.num_chips, 2
            )
        if self.monitor is not None:
            rec.update(self.monitor.round_fields(
                round_idx, loss,
                round_seconds=None if is_tail else dt))
        tele = self.telemetry
        if not is_tail:
            # Tails would bury the real per-round time under µs callback
            # dts (R-1 of every R observations in a blocked run).
            tele.histogram("round_seconds").observe(dt)
        tele.gauge("loss").set(rec["loss"])
        tele.counter("rounds").add(1)
        self.records.append(rec)
        if self._file:
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        return None

    def close(self) -> None:
        """Flush the telemetry summary and release the file. Idempotent —
        trainer paths call it from ``finally`` AND the happy path."""
        if self._file:
            from distkeras_tpu.telemetry.exporters import write_jsonl

            # The aggregate dump rides the same JSONL: rounds + phases in one
            # file is what the report CLI renders. Windowed to this logger's
            # lifetime so back-to-back runs don't cross-contaminate.
            with contextlib.suppress(Exception):
                write_jsonl(self.telemetry, self._file, since=self._mark)
            self._file.close()
            self._file = None

    #: callbacks arriving within this window of their predecessor are part
    #: of the same dispatch burst (blocked/auto execution delivers one
    #: callback burst per compiled block). Shared constant: the live
    #: straggler monitor and the offline report segment by the same value.
    from distkeras_tpu.telemetry.core import (  # noqa: F401 - class-attr re-export
        BURST_EPS_S as _BURST_EPS_S)

    def mean_throughput(self, skip: int = 1) -> float:
        """Aggregate samples/sec, skipping the first ``skip`` timing
        segments (compile/warmup). Blocked and auto execution deliver
        callbacks in per-block bursts — a burst's first record absorbs the
        whole block's duration and the rest read ~0 s — so records are
        grouped into segments (a timing boundary plus its burst tail) and
        throughput is computed from segment totals: per-round rates or raw
        record sums would misattribute samples across block boundaries.
        The grouping is ``telemetry.report.throughput_segments`` — ONE
        implementation, so the live number and the offline report cannot
        diverge."""
        from distkeras_tpu.telemetry.report import throughput_segments

        segments = throughput_segments(
            [r for r in self.records if "samples_per_sec" in r])
        if len(segments) > skip:
            segments = segments[skip:]
        # else: everything landed in <= skip segments (e.g. one giant block)
        # — report over what exists rather than a meaningless 0.
        total_t = sum(s["seconds"] for s in segments)
        total_rounds = sum(s["rounds"] for s in segments)
        if not segments or total_t <= 0:
            return 0.0
        return self.samples_per_round * total_rounds / total_t


def scaling_efficiency(sps_n: float, sps_1: float, n_chips: int) -> float:
    """BASELINE.md's headline metric: throughput(N) / (N * throughput(1))."""
    if sps_1 <= 0 or n_chips <= 0:
        return 0.0
    return sps_n / (n_chips * sps_1)


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """``jax.profiler`` span -> Perfetto/XProf trace in ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
