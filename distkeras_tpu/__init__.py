"""distkeras_tpu — a TPU-native distributed deep-learning framework.

A from-scratch rebuild of the capabilities of ``xclmj/dist-keras`` (the Spark-based
asynchronous-SGD framework for Keras; see SURVEY.md for the full structural analysis of
the reference) on JAX/XLA:

* The reference's Spark-executor **workers** (``distkeras/workers.py`` -> ``Worker``,
  ``ADAGWorker``, ``AEASGDWorker``...) become per-chip model replicas running
  jit-compiled local-step loops (:mod:`distkeras_tpu.workers`).
* The reference's socket-served **parameter servers**
  (``distkeras/parameter_servers.py`` -> ``DeltaParameterServer``,
  ``ADAGParameterServer``, ``DynSGDParameterServer``) become deterministic ICI
  collective *folds* of worker deltas into a replicated center variable
  (:mod:`distkeras_tpu.parallel.disciplines`).
* The reference's pickle-over-TCP **networking** (``distkeras/networking.py``) becomes
  XLA collectives (``psum`` / ``all_gather`` / ``ppermute``) over a
  :class:`jax.sharding.Mesh` (:mod:`distkeras_tpu.runtime.mesh`).
* The reference's Spark **DataFrame data plane** (``distkeras/transformers.py``,
  ``utils.py``) becomes a columnar, numpy-backed frame with the same transformer set
  (:mod:`distkeras_tpu.data`).
* The **trainer taxonomy** (``distkeras/trainers.py`` -> ``SingleTrainer``,
  ``DOWNPOUR``, ``ADAG``, ``DynSGD``, ``AEASGD``, ``EAMSGD``, ``AveragingTrainer``,
  ``EnsembleTrainer``) is kept class-for-class with the same constructor-kwargs
  surface and the same ``Trainer.train(dataframe)`` entry point
  (:mod:`distkeras_tpu.trainers`).
"""

__version__ = "0.5.0"

from distkeras_tpu.runtime.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    PIPE_AXIS,
    EXPERT_AXIS,
    data_mesh,
    hybrid_mesh,
    device_count,
)
from distkeras_tpu.runtime.serialization import (  # noqa: F401
    serialize_model,
    deserialize_model,
    serialize_params,
    deserialize_params,
)

from distkeras_tpu.trainers import (  # noqa: F401
    ADAG,
    AEASGD,
    AveragingTrainer,
    DOWNPOUR,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    ParallelTrainer,
    SingleTrainer,
    SynchronousDistributedTrainer,
    Trainer,
    TransformerTrainer,
)
from distkeras_tpu.data import (  # noqa: F401
    DataFrame,
    ShardedDataFrame,
    ShardStore,
    ShardWriter,
    merge_manifests,
    write_shards,
    DenseTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    ReshapeTransformer,
    Transformer,
)
from distkeras_tpu.models import Model  # noqa: F401
from distkeras_tpu.predictors import (  # noqa: F401
    ClassPredictor,
    ModelPredictor,
    ProbabilityPredictor,
)
from distkeras_tpu.evaluators import (  # noqa: F401
    AccuracyEvaluator,
    F1Evaluator,
    LossEvaluator,
)
from distkeras_tpu.resilience import (  # noqa: F401
    FaultPlan,
    Supervisor,
    supervise,
)
from distkeras_tpu.fleet import (  # noqa: F401
    ElasticTraining,
    FleetJob,
    FleetScheduler,
)

__all__ = [
    "Trainer",
    "SingleTrainer",
    "SynchronousDistributedTrainer",
    "DOWNPOUR",
    "ADAG",
    "DynSGD",
    "AEASGD",
    "EAMSGD",
    "AveragingTrainer",
    "EnsembleTrainer",
    "ParallelTrainer",
    "TransformerTrainer",
    "DataFrame",
    "ShardedDataFrame",
    "ShardStore",
    "ShardWriter",
    "merge_manifests",
    "write_shards",
    "Transformer",
    "LabelIndexTransformer",
    "OneHotTransformer",
    "MinMaxTransformer",
    "ReshapeTransformer",
    "DenseTransformer",
    "ModelPredictor",
    "ProbabilityPredictor",
    "ClassPredictor",
    "AccuracyEvaluator",
    "F1Evaluator",
    "LossEvaluator",
    "FaultPlan",
    "Supervisor",
    "supervise",
    "FleetScheduler",
    "FleetJob",
    "ElasticTraining",
    "Model",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "PIPE_AXIS",
    "EXPERT_AXIS",
    "data_mesh",
    "hybrid_mesh",
    "device_count",
    "serialize_model",
    "deserialize_model",
    "serialize_params",
    "deserialize_params",
]
