// Native data-plane kernels: multithreaded row gather for batch planning.
//
// The reference delegates its data plane to Spark's JVM (partition shuffle and
// per-executor iterators, SURVEY.md L1/external substrate); the TPU rebuild's
// equivalent host-side hot path is materializing each fold round's
// [workers, window, batch, ...] array from the index matrix
// (distkeras_tpu/data/batching.py -> BatchPlan.round). numpy's fancy indexing
// is single-threaded and holds the GIL; this gather releases it across a small
// thread pool so the feed keeps up with the device and overlaps with dispatch.
//
// Build: g++ -O3 -march=native -shared -fPIC -o _loader.so loader.cc -lpthread
// (distkeras_tpu/data/native_loader.py does this on demand and caches the .so)

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ABI contract between this translation unit and the ctypes declarations in
// distkeras_tpu/data/native_loader.py (_ABI_VERSION). Bump BOTH on any
// signature change; the Python side refuses to load a mismatched .so and
// falls back to numpy instead of calling through a stale prototype.
int dk_abi_version() { return 2; }

// Gather rows: out[i, :] = src[idx[i], :] for i in [0, n_idx).
// row_bytes is the size of one row in bytes; src has n_rows rows.
// Returns 0 on success, -1 on out-of-range index (out contents undefined).
int dk_gather_rows(const uint8_t* src, int64_t n_rows, int64_t row_bytes,
                   const int64_t* idx, int64_t n_idx, uint8_t* out,
                   int num_threads) {
  if (num_threads < 1) num_threads = 1;
  std::atomic<int> bad{0};
  auto worker = [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      const int64_t r = idx[i];
      if (r < 0 || r >= n_rows) {
        bad.store(1, std::memory_order_relaxed);
        return;
      }
      std::memcpy(out + i * row_bytes, src + r * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  };
  if (num_threads == 1 || n_idx < 4 * num_threads) {
    worker(0, n_idx);
  } else {
    std::vector<std::thread> threads;
    const int64_t chunk = (n_idx + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      const int64_t begin = t * chunk;
      const int64_t end = begin + chunk < n_idx ? begin + chunk : n_idx;
      if (begin >= end) break;
      threads.emplace_back(worker, begin, end);
    }
    for (auto& th : threads) th.join();
  }
  return bad.load() ? -1 : 0;
}

// Normalize float32 rows: out = (x - offset) * scale + bias.
// The MinMaxTransformer hot loop for large frames. bias is applied separately
// (NOT folded into offset) to avoid catastrophic cancellation when scale is
// huge (degenerate input ranges).
void dk_scale_f32(const float* src, int64_t n, float offset, float scale,
                  float bias, float* out, int num_threads) {
  if (num_threads < 1) num_threads = 1;
  auto worker = [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i)
      out[i] = (src[i] - offset) * scale + bias;
  };
  if (num_threads == 1 || n < 1 << 16) {
    worker(0, n);
  } else {
    std::vector<std::thread> threads;
    const int64_t chunk = (n + num_threads - 1) / num_threads;
    for (int t = 0; t < num_threads; ++t) {
      const int64_t begin = t * chunk;
      const int64_t end = begin + chunk < n ? begin + chunk : n;
      if (begin >= end) break;
      threads.emplace_back(worker, begin, end);
    }
    for (auto& th : threads) th.join();
  }
}

}  // extern "C"
