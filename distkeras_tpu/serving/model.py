"""Bucketed-shape inference: one jit program per batch bucket, zero
retraces after warmup.

A live request stream produces ragged batch sizes — 3 rows now, 17 rows
next — and a naive ``jit(apply)`` would recompile on every new size,
turning tail latency into compile latency. Instead every micro-batch is
padded up to the smallest bucket from ``DKTPU_SERVE_BUCKETS`` that fits
it, so the jit cache holds exactly ``len(buckets)`` programs, all compiled
at warmup (SNIPPETS.md [2]'s sharding-spec helpers are the same idea
applied to shape buckets). A compile observed *after* warmup is a contract
violation and fires the ``serving.retrace_after_warmup`` counter — the
chaos smoke asserts it stays at zero.

Compiles are counted with a trace-time Python side effect (the counter in
the traced function body runs once per compilation, never per call), which
is version-proof against jax's private cache-introspection surface.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from distkeras_tpu.serving.batcher import bucket_for


class BucketedModel:
    """A :class:`~distkeras_tpu.models.base.Model` wrapped for serving:
    padded-bucket jit forward, warmup over every bucket, retrace
    accounting. Parameters are swappable (:meth:`set_params`) without
    recompiling — the cache is keyed on shapes, and a hot-swapped
    checkpoint has the same tree structure by construction."""

    def __init__(self, model, buckets: Sequence[int]):
        import jax

        self.model = model
        self.buckets = tuple(buckets)
        self.params = model.params
        self._compiles = 0
        self._warmed = False

        def _traced(params, *inputs):
            # Trace-time side effect: runs once per compilation. After
            # warmup this must be unreachable — every shape in flight is a
            # bucket shape already compiled.
            self._on_trace()
            return model.apply(params, *inputs, train=False)

        self._fwd = jax.jit(_traced)

    def _on_trace(self) -> None:
        from distkeras_tpu import telemetry

        self._compiles += 1
        if self._warmed:
            telemetry.counter("serving.retrace_after_warmup").add(1)
            telemetry.event("serve_retrace", {"compiles": self._compiles})

    # -- lifecycle ----------------------------------------------------------

    def warmup(self) -> int:
        """Compile every bucket's program on zeros shaped from the model's
        ``sample_spec`` (its build-time input signature). Returns the
        number of programs compiled; after this, any further compile is a
        counted retrace. Doubles as the hot-swap *probe*: restored params
        that cannot produce a finite forward pass raise here, and the
        registry keeps the old version."""
        spec = self.model.sample_spec
        if spec is None:
            raise ValueError(
                "BucketedModel.warmup needs model.sample_spec (models from "
                "Model.build carry one) to know the per-row input shapes")
        before = self._compiles
        for b in self.buckets:
            inputs = tuple(np.zeros((b,) + tuple(s.shape[1:]), s.dtype)
                           for s in spec)
            out = np.asarray(self._fwd(self.params, *inputs))
            if not np.all(np.isfinite(out)):
                raise ValueError(
                    f"warmup probe produced non-finite outputs at bucket "
                    f"{b}: refusing to serve these parameters")
        self._warmed = True
        return self._compiles - before

    @property
    def warmed(self) -> bool:
        return self._warmed

    def compiles(self) -> int:
        """Total compilations so far (warmup included)."""
        return self._compiles

    def set_params(self, params) -> None:
        """Swap in new parameters — an attribute store, atomic under the
        GIL; the next batch picks them up, no recompile (same tree, same
        shapes)."""
        self.params = params

    # -- inference ----------------------------------------------------------

    def infer(self, arrays: Sequence[np.ndarray],
              rows: Optional[int] = None) -> np.ndarray:
        """Forward ``arrays`` (leading axis = rows) padded up to the
        smallest fitting bucket; the padding rows are sliced back off the
        output, so callers only ever see their own rows."""
        arrays = tuple(np.asarray(a) for a in arrays)
        n = int(arrays[0].shape[0]) if rows is None else int(rows)
        bucket = bucket_for(n, self.buckets)
        if bucket is None:
            raise ValueError(
                f"batch of {n} rows exceeds the largest bucket "
                f"{self.buckets[-1]} (the batcher caps batches below this)")
        if bucket != n:
            arrays = tuple(
                np.concatenate(
                    [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)])
                for a in arrays)
        out = np.asarray(self._fwd(self.params, *arrays))
        return out[:n]
