"""Continuous micro-batching with admission control.

The frontend's handler threads each carry ONE in-flight request; this
module is where those concurrent requests meet. A handler *submits* its
request (admission control happens right there — a request that would
overflow the queue bound is shed with :class:`OverloadedError` before any
of it is queued) and blocks on the request's event; the dispatch thread
*collects* whatever is queued, waits up to the latency budget
(``DKTPU_SERVE_MAX_WAIT_MS``) for stragglers to coalesce, and hands one
batch to the model. The batch is capped at the largest shape bucket
(``DKTPU_SERVE_BUCKETS``) so padding — done by the model wrapper, not
here — always lands on a compiled shape.

Accounting contract (asserted by the chaos smoke): every request either
fails admission with a typed error and is never queued, or is accepted and
later answered — with a result, a :class:`DeadlineExceededError` (it aged
past ``DKTPU_SERVE_DEADLINE_MS`` while queued), or a
:class:`ModelUnavailableError` (the batcher closed under it). There is no
path on which an accepted request is dropped without a reply.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from distkeras_tpu.runtime import config
from distkeras_tpu.serving.errors import (
    DeadlineExceededError,
    ModelUnavailableError,
    OverloadedError,
)


def parse_buckets(spec: Optional[str] = None) -> tuple[int, ...]:
    """``DKTPU_SERVE_BUCKETS`` -> strictly-increasing positive batch sizes
    (one jit program per bucket; the last one is the per-batch row cap)."""
    spec = config.env_str("DKTPU_SERVE_BUCKETS") if spec is None else spec
    try:
        buckets = tuple(int(b.strip()) for b in spec.split(",") if b.strip())
    except ValueError as e:
        raise ValueError(f"malformed DKTPU_SERVE_BUCKETS {spec!r}: {e}") from e
    if not buckets:
        raise ValueError(f"no buckets in DKTPU_SERVE_BUCKETS {spec!r}")
    if any(b <= 0 for b in buckets) or list(buckets) != sorted(set(buckets)):
        raise ValueError(
            f"DKTPU_SERVE_BUCKETS must be strictly-increasing positive "
            f"sizes, got {spec!r}")
    return buckets


def bucket_for(rows: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket that fits ``rows`` (None when even the largest is
    too small — the admission-time size rejection)."""
    for b in buckets:
        if rows <= b:
            return b
    return None


class PendingRequest:
    """One accepted request riding through the batcher: its input arrays,
    its admission timestamp (the latency span origin), and the event its
    handler thread blocks on until ``result``/``error`` is set."""

    __slots__ = ("arrays", "rows", "admitted_at", "admitted_wall",
                 "deadline_at", "event", "result", "error", "version",
                 "trace")

    def __init__(self, arrays: Sequence, rows: int,
                 deadline_s: Optional[float] = None):
        self.arrays = tuple(arrays)
        self.rows = int(rows)
        self.admitted_at = time.monotonic()
        #: wall-clock twin of ``admitted_at`` — trace spans are stamped in
        #: wall time so the collector can align them across processes.
        self.admitted_wall = time.time()
        #: the request's :class:`~distkeras_tpu.telemetry.tracing.
        #: TraceContext` (set by the frontend when the wire header carried
        #: one); the dispatch thread emits its queue/batch spans under it.
        self.trace = None
        self.deadline_at = (self.admitted_at + deadline_s
                            if deadline_s is not None else None)
        self.event = threading.Event()
        self.result = None      # per-request output arrays on success
        self.error: Optional[BaseException] = None
        self.version = None     # model version that answered

    def answer(self, result=None, error: Optional[BaseException] = None,
               version=None) -> None:
        self.result = result
        self.error = error
        self.version = version
        self.event.set()


class MicroBatcher:
    """Bounded FIFO of :class:`PendingRequest` with the shed-before-accept
    admission check at ``submit`` and the coalescing wait in ``collect``."""

    def __init__(self, buckets: Sequence[int],
                 max_queue_rows: Optional[int] = None,
                 max_wait_s: Optional[float] = None,
                 deadline_s: Optional[float] = None):
        self.buckets = tuple(buckets)
        self.max_rows = int(config.env_int("DKTPU_SERVE_QUEUE")
                            if max_queue_rows is None else max_queue_rows)
        if max_wait_s is None:
            max_wait_s = config.env_float("DKTPU_SERVE_MAX_WAIT_MS") / 1e3
        self.max_wait_s = float(max_wait_s)
        if deadline_s is None:
            ms = config.env_float("DKTPU_SERVE_DEADLINE_MS")
            deadline_s = ms / 1e3 if ms is not None else None
        self.deadline_s = deadline_s
        self._queue: list[PendingRequest] = []
        self._rows = 0
        self._cond = threading.Condition()
        self._closed = False

    # -- handler side -------------------------------------------------------

    def submit(self, arrays: Sequence, rows: int) -> PendingRequest:
        """Admission control: accept ``arrays`` into the queue or shed with
        a typed error BEFORE anything is queued. Returns the accepted
        request; the caller blocks on its event."""
        from distkeras_tpu import telemetry

        if bucket_for(rows, self.buckets) is None:
            telemetry.counter("serving.shed").add(1)
            raise OverloadedError(
                f"request of {rows} rows exceeds the largest serving "
                f"bucket ({self.buckets[-1]}); split it client-side")
        with self._cond:
            if self._closed:
                raise ModelUnavailableError("serving frontend is closed")
            if self._rows + rows > self.max_rows:
                telemetry.counter("serving.shed").add(1)
                raise OverloadedError(
                    f"serving queue full ({self._rows}/{self.max_rows} "
                    f"rows); request of {rows} rows shed")
            pending = PendingRequest(arrays, rows, deadline_s=self.deadline_s)
            self._queue.append(pending)
            self._rows += rows
            telemetry.counter("serving.accepted").add(1)
            telemetry.gauge("serving.queue_depth").set(float(self._rows))
            self._cond.notify_all()
        return pending

    # -- dispatch side ------------------------------------------------------

    def collect(self, poll_s: float = 0.2) -> list[PendingRequest]:
        """One micro-batch: block (up to ``poll_s``) for a first request,
        then keep coalescing until the latency budget elapses or the batch
        reaches the largest bucket. Expired requests are answered with
        :class:`DeadlineExceededError` here — the queue never computes work
        nobody is waiting for. Returns [] on poll timeout / close."""
        from distkeras_tpu import telemetry

        with self._cond:
            if not self._queue:
                self._cond.wait(timeout=poll_s)
            if not self._queue:
                return []
            batch_deadline = time.monotonic() + self.max_wait_s
            while True:
                self._expire_locked(telemetry)
                rows = sum(p.rows for p in self._queue)
                if rows >= self.buckets[-1] or self._closed:
                    break
                remaining = batch_deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            # Pop FIFO whole-requests up to the row cap (a request's rows
            # are never split across batches — its reply is one frame).
            batch: list[PendingRequest] = []
            taken = 0
            while self._queue:
                nxt = self._queue[0]
                if batch and taken + nxt.rows > self.buckets[-1]:
                    break
                batch.append(self._queue.pop(0))
                taken += nxt.rows
            self._rows -= taken
            telemetry.gauge("serving.queue_depth").set(float(self._rows))
        return batch

    def _expire_locked(self, telemetry) -> None:
        """Answer queued requests that aged past their deadline (typed
        reply, never a silent drop). Caller holds the condition lock."""
        if self.deadline_s is None or not self._queue:
            return
        now = time.monotonic()
        live = []
        for p in self._queue:
            if p.deadline_at is not None and now > p.deadline_at:
                self._rows -= p.rows
                telemetry.counter("serving.deadline_drops").add(1)
                p.answer(error=DeadlineExceededError(
                    f"request aged {(now - p.admitted_at) * 1e3:.1f}ms in "
                    f"queue, past its {self.deadline_s * 1e3:.1f}ms deadline"))
            else:
                live.append(p)
        self._queue[:] = live
        telemetry.gauge("serving.queue_depth").set(float(self._rows))

    def depth_rows(self) -> int:
        with self._cond:
            return self._rows

    def close(self) -> None:
        """Stop admitting; answer everything still queued with a typed
        :class:`ModelUnavailableError` — the accepted-never-dropped
        contract holds through shutdown."""
        with self._cond:
            self._closed = True
            for p in self._queue:
                p.answer(error=ModelUnavailableError(
                    "serving frontend closed before this request was "
                    "dispatched"))
            self._queue.clear()
            self._rows = 0
            self._cond.notify_all()
