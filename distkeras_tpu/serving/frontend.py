"""The serving frontend: live requests in, micro-batched answers out.

Speaks the hardened netps wire protocol (``netps/wire.py`` — length
prefix, crc32, request-id echo) on a TCP listener whose port comes from
the bind-probed fleet pool (``fleet/ports.py``) and is released at
teardown. One handler thread per connection, exactly like ``PSServer``;
but where the PS answers each request inline, an ``infer`` handler
*submits* its rows to the :class:`~distkeras_tpu.serving.batcher.
MicroBatcher` and blocks — the dispatch thread coalesces concurrent
requests into one padded-bucket forward pass on the registry's live model
and fans the rows back out.

Chaos hooks (``DKTPU_NET_FAULTS``): ``serve_drop@F`` kills request F's
connection before admission (the client fails over and retries — the
request was never accepted, so the accounting contract is untouched);
``serve_slow@F:S`` holds request F's reply for S seconds after compute
(tail-latency injection). F indexes accepted ``infer`` requests
process-wide across every frontend, like the PS-side fault indices.

:class:`ServeClient` is the other half: the PSClient idiom shrunk to the
two serving ops — per-attempt deadline, full-jitter backoff, endpoint
walking over ``wire.split_endpoints`` on connection failure (HA across a
replica set), request-id echo matching, and typed error replies raised as
the exceptions in ``serving/errors.py`` (never retried: the server
answered).
"""

from __future__ import annotations

import itertools
import json
import socket
import threading
import time
from typing import Optional

import numpy as np

from distkeras_tpu.fleet import ports
from distkeras_tpu.netps import wire
from distkeras_tpu.netps.endpoints import EndpointWalker
from distkeras_tpu.netps.errors import ProtocolError, RPCTimeoutError
from distkeras_tpu.resilience import faults as _faults
from distkeras_tpu.resilience.backoff import full_jitter
from distkeras_tpu.runtime import config
from distkeras_tpu.serving import errors as serrors
from distkeras_tpu.serving.batcher import MicroBatcher
from distkeras_tpu.telemetry import tracing

_POLL_S = 0.2
_FRAME_COMPLETE_S = 30.0

#: process-wide accepted-``infer`` index the chaos kinds key on — shared
#: across frontends like the PS-side fault indices are shared across
#: servers, so a replica-set smoke can address "the 7th request" without
#: caring which replica catches it.
_REQ_INDEX = itertools.count()


def reset_request_index() -> None:
    """Tests/smokes re-arm fault indices from zero."""
    global _REQ_INDEX
    _REQ_INDEX = itertools.count()


class ServingFrontend:
    """One serving replica: listener + handlers + dispatch loop over a
    :class:`~distkeras_tpu.serving.registry.ModelRegistry`."""

    def __init__(self, registry, host: str = "127.0.0.1",
                 port: Optional[int] = None,
                 max_wait_s: Optional[float] = None,
                 max_queue_rows: Optional[int] = None,
                 deadline_s: Optional[float] = None):
        self.registry = registry
        self.host = host
        # Bind-probed pool port unless the caller pins one (tests); pool
        # ports are released at close so a torn-down replica's port is
        # immediately reusable (the PR 8 PS/coordinator fix, applied here
        # from day one).
        self._port_owned = port is None
        self.port = ports.reserve_port(host) if port is None else int(port)
        self.batcher = MicroBatcher(
            registry.buckets, max_queue_rows=max_queue_rows,
            max_wait_s=max_wait_s, deadline_s=deadline_s)
        self.served = 0
        self._listener: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def ready(self) -> bool:
        """Liveness vs readiness: a started replica answers stats (live)
        but is only *ready* once the registry holds a warmed model and no
        swap probe is in flight — the window where an infer would block
        on warmup compile is exactly what health-aware clients skip."""
        return (self._started and not self._stop.is_set()
                and not getattr(self.registry, "warming", False))

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ServingFrontend":
        if self._started:
            return self
        self._started = True
        from distkeras_tpu.telemetry.vitals import start_vitals

        start_vitals()  # no-op unless DKTPU_VITALS_S is set
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(64)
        self._listener.settimeout(_POLL_S)
        for name, target in (("serve-accept", self._accept_loop),
                             ("serve-dispatch", self._dispatch_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def close(self) -> None:
        """Graceful teardown: stop admitting, answer the queue out with
        typed errors, join every thread, release the pool port."""
        self._stop.set()
        self.batcher.close()
        self._teardown_sockets()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=10.0)
        self._threads.clear()
        if self._port_owned:
            ports.release_port(self.port)
            self._port_owned = False

    def kill(self) -> None:
        """Crash simulation (chaos): drop the listener and every live
        connection mid-stream, no typed replies, no drain — clients see
        ConnectionError and walk to the next replica. The pool port is
        still released (the *process* is fine, the replica died)."""
        self._stop.set()
        self._teardown_sockets()
        self.batcher.close()
        if self._port_owned:
            ports.release_port(self.port)
            self._port_owned = False

    def _teardown_sockets(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    # -- accept + handler ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            listener = self._listener
            if listener is None:
                return
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="serve-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _handle(self, conn: socket.socket) -> None:
        from distkeras_tpu import telemetry

        try:
            while not self._stop.is_set():
                conn.settimeout(_POLL_S)
                try:
                    prefix = wire.recv_exact(conn, wire.PREFIX_SIZE)
                except socket.timeout:
                    continue
                except (ConnectionError, OSError):
                    return
                conn.settimeout(_FRAME_COMPLETE_S)
                kind, _n, header, arrays = wire.finish_frame(conn, prefix)
                if kind != wire.KIND_REQUEST:
                    raise ProtocolError(
                        f"serving frontend got frame kind {kind}, "
                        f"expected a request")
                if not self._serve_request(conn, header, arrays):
                    return
        except (ProtocolError, ConnectionError, OSError):
            telemetry.counter("serving.conn_errors").add(1)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_request(self, conn, header: dict, arrays: list) -> bool:
        """Answer one request frame; False = drop the connection (chaos)."""
        from distkeras_tpu import telemetry

        op = header.get("op")
        req = header.get("req")
        if op == wire.OP_STATS:
            st1 = time.time() if "ct0" in header else None
            b, version = self.registry.current()
            n = max(0, int(header.get("ring", 0) or 0))
            # Ring records may carry non-JSON payloads (exception reprs);
            # round-trip through default=str so one odd record cannot
            # poison the stats reply frame.
            ring = json.loads(json.dumps(tracing.ring_head(n),
                                         default=str)) if n else []
            reply = {
                "op": op, "req": req, "version": version,
                "queue_rows": self.batcher.depth_rows(),
                "served": self.served, "compiles": b.compiles(),
                "caps": wire.CAPS, "role": tracing.role(),
                # Readiness contract: a replica mid-warmup/mid-swap (the
                # registry holds no probed model yet) answers stats but
                # reports not-ready so health-aware clients walk past it.
                "ready": self.ready,
                "snapshot": telemetry.get().snapshot(),
                "ring": ring}
            if st1 is not None:
                # Same NTP-style exchange the PS `_serve_frame` does: echo
                # receive/send stamps so the health hub (and the tracing
                # collector) can estimate this replica's clock offset.
                reply["st1"] = st1
                reply["st2"] = time.time()
            wire.send_frame(conn, wire.KIND_REPLY, reply, [])
            return True
        if op != wire.OP_INFER:
            wire.send_frame(conn, wire.KIND_REPLY, {
                "error": "unknown_op", "req": req,
                "message": f"unknown serving op {op!r}"}, [])
            return True
        if not arrays:
            wire.send_frame(conn, wire.KIND_REPLY, {
                "error": "serving", "req": req,
                "message": "infer request carried no input arrays"}, [])
            return True
        idx = next(_REQ_INDEX)
        plan = _faults.active_net_plan()
        if plan is not None and plan.fire("serve_drop", idx) is not None:
            return False  # pre-admission: connection dies, nothing queued
        slow = plan.fire("serve_slow", idx) if plan is not None else None
        # Wire arrays view the per-frame buffer; copy before they outlive
        # this handler's frame (the dispatch thread concatenates later).
        inputs = tuple(np.array(a, copy=True) for a in arrays)
        tctx = tracing.header_ctx(header)
        try:
            pending = self.batcher.submit(inputs, int(inputs[0].shape[0]))
            pending.trace = tctx
        except serrors.ServingError as e:
            wire.send_frame(conn, wire.KIND_REPLY, {
                "error": serrors.error_kind(e), "req": req,
                "message": str(e)}, [])
            return True
        pending.event.wait()
        if slow is not None:
            time.sleep(slow)
        elapsed = time.monotonic() - pending.admitted_at
        telemetry.histogram("serving.latency").observe(elapsed)
        telemetry.counter("serving.answered").add(1)
        if pending.error is not None:
            wire.send_frame(conn, wire.KIND_REPLY, {
                "error": serrors.error_kind(pending.error), "req": req,
                "message": str(pending.error)}, [])
            return True
        self.served += 1
        wire.send_frame(conn, wire.KIND_REPLY, {
            "op": op, "req": req, "version": pending.version},
            [np.ascontiguousarray(pending.result)])
        return True

    # -- dispatch -----------------------------------------------------------

    def _dispatch_loop(self) -> None:
        from distkeras_tpu import telemetry

        while not self._stop.is_set():
            batch = self.batcher.collect(poll_s=_POLL_S)
            if not batch:
                continue
            bucketed, version = self.registry.current()
            rows = sum(p.rows for p in batch)
            d_wall, d0 = time.time(), time.perf_counter()
            try:
                with telemetry.span("serving.dispatch"):
                    joined = tuple(
                        np.concatenate([p.arrays[i] for p in batch])
                        for i in range(len(batch[0].arrays)))
                    out = bucketed.infer(joined, rows=rows)
            except Exception as e:  # noqa: BLE001 - answer, don't drop
                for p in batch:
                    p.answer(error=serrors.ServingError(
                        f"dispatch failed: {type(e).__name__}: {e}"))
                continue
            d_dur = time.perf_counter() - d0
            for p in batch:
                if p.trace is not None:
                    # Two server-side segments per traced request: how
                    # long it queued behind the coalescing wait, and the
                    # shared forward pass it rode (same span per batch
                    # member — the batch IS the shared resource).
                    tracing.emit("serve.queue", p.trace, p.admitted_wall,
                                 max(0.0, d_wall - p.admitted_wall),
                                 rows=p.rows)
                    tracing.emit("serve.batch", p.trace, d_wall, d_dur,
                                 rows=rows, requests=len(batch),
                                 version=version)
            telemetry.counter("serving.batches").add(1)
            telemetry.counter("serving.batched_rows").add(rows)
            from distkeras_tpu.serving.batcher import bucket_for

            bucket = bucket_for(rows, bucketed.buckets)
            if bucket is not None:
                telemetry.counter("serving.padded_rows").add(bucket - rows)
            off = 0
            for p in batch:
                p.answer(result=out[off:off + p.rows], version=version)
                off += p.rows


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

#: typed reply kinds -> exceptions. ``from_reply`` marks "the server
#: answered" — never retried, matching the PSClient convention.
_ERROR_TYPES = {
    "overloaded": serrors.OverloadedError,
    "deadline": serrors.DeadlineExceededError,
    "unavailable": serrors.ModelUnavailableError,
    "unknown_op": serrors.ServingError,
    "serving": serrors.ServingError,
}


class ServeClient:
    """Inference client for a replica set: ``"host:port[,host:port...]"``
    endpoints walked in order on connection failure, typed server errors
    raised immediately."""

    def __init__(self, endpoints: str, timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None):
        #: shared failover mechanics (``netps/endpoints.py``): split order
        #: and walk semantics are the same contract PSClient rides.
        self._walker = EndpointWalker(endpoints)
        self.timeout = (timeout if timeout is not None
                        else config.env_float("DKTPU_NET_TIMEOUT"))
        self.retries = (retries if retries is not None
                        else config.env_int("DKTPU_NET_RETRIES"))
        self.backoff = (backoff if backoff is not None
                        else config.env_float("DKTPU_NET_BACKOFF"))
        self._sock: Optional[socket.socket] = None
        self._req = itertools.count()
        self._lock = threading.Lock()
        #: capability map learned from the first traced request's ``stats``
        #: exchange (serving has no ``join``); None = not yet asked.
        self._peer_caps: Optional[dict] = None

    @property
    def endpoints(self) -> list:
        """Ordered (host, port) replica list (compat alias)."""
        return self._walker.endpoints

    @property
    def _idx(self) -> int:
        return self._walker.index

    # -- transport ----------------------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        host, port = self._walker.current()
        sock = socket.create_connection((host, port), timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        return sock

    def _fail_over(self) -> None:
        """Drop the connection and advance to the next endpoint — the HA
        walk (``wire.split_endpoints`` order: primary, then the rest).
        ``advance`` is the unconditional single-threaded form: one request
        in flight under ``_lock``, every failure is ours."""

        def teardown():
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

        self._walker.advance(on_walk=teardown)

    def _rpc(self, header: dict, arrays) -> tuple[dict, list]:
        from distkeras_tpu import telemetry

        last = None
        with self._lock:
            for attempt in range(self.retries):
                deadline = time.monotonic() + self.timeout
                req = next(self._req)
                header = dict(header, req=req)
                try:
                    sock = self._connect()
                    sock.settimeout(self.timeout)
                    wire.send_frame(sock, wire.KIND_REQUEST, header, arrays)
                    while True:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise socket.timeout("reply deadline exhausted")
                        sock.settimeout(remaining)
                        kind, rhdr, rarrays = wire.read_frame(sock)
                        if kind != wire.KIND_REPLY:
                            raise ProtocolError(
                                f"expected a reply frame, got kind {kind}")
                        if rhdr.get("req") == req:
                            break
                        # stale reply (reconnect raced an old answer):
                        # discard and keep reading inside the deadline.
                    err = rhdr.get("error")
                    if err is not None:
                        exc = _ERROR_TYPES.get(err, serrors.ServingError)(
                            rhdr.get("message", err))
                        exc.from_reply = True
                        raise exc
                    return rhdr, rarrays
                except serrors.ServingError:
                    raise  # the server answered: typed, never retried
                except (ConnectionError, ProtocolError, socket.timeout,
                        OSError) as e:
                    last = e
                    telemetry.counter("serving.client_failovers").add(1)
                    self._fail_over()
                    time.sleep(full_jitter(self.backoff,
                                           min(attempt, 6)))
        raise RPCTimeoutError(
            f"serving rpc failed after {self.retries} attempts over "
            f"{len(self.endpoints)} endpoint(s): {last!r}",
            attempts=self.retries)

    # -- ops ----------------------------------------------------------------

    def _traced(self, header: dict) -> dict:
        """Trace-context wire fields, gated on the replica set having
        advertised ``CAPS["tracing"]`` — a peer that never did is sent
        zero new bytes (absent JSON keys), same rule as PSClient."""
        if tracing.enabled() and (self._peer_caps or {}).get("tracing"):
            header.update(tracing.wire_fields())
        return header

    def _learn_caps(self) -> None:
        """One-shot capability discovery: serving has no ``join``
        handshake, so the first traced ``infer`` asks ``stats`` for the
        peer's CAPS. A failed probe records ``{}`` (trace locally, send
        nothing) — the data path must not inherit the probe's failure."""
        if self._peer_caps is not None or not tracing.enabled():
            return
        try:
            self._peer_caps = dict(self.stats().get("caps") or {})
        except (serrors.ServingError, RPCTimeoutError, OSError):
            self._peer_caps = {}

    def infer(self, *arrays) -> tuple[np.ndarray, int]:
        """One inference round-trip: ``(outputs, model_version)`` for the
        caller's rows (leading axis)."""
        arrays = tuple(np.ascontiguousarray(a) for a in arrays)
        rows = int(arrays[0].shape[0]) if arrays and arrays[0].ndim else 0
        self._learn_caps()
        with tracing.trace_scope("serve.request", rows=rows):
            with tracing.child_scope("serve.wire"):
                header, out = self._rpc(
                    self._traced({"op": wire.OP_INFER}), arrays)
        return out[0], int(header.get("version", -1))

    def stats(self, ring: int = 0) -> dict:
        """The replica's live stats frame; ``ring`` > 0 also returns the
        head of its flight-recorder ring (the scrape CLI's path)."""
        header, _ = self._rpc(
            {"op": wire.OP_STATS, **({"ring": int(ring)} if ring else {})},
            [])
        return header

    def prefer_ready(self, probe_timeout: float = 0.5) -> list:
        """Health-aware walk ordering: one short stats probe per replica,
        then park the walker on the first *ready* one — warming/swapping
        replicas (``ready: false``) and unreachable ones sink to the back
        of the failover order instead of eating the first attempts.

        Best-effort by design: probes that fail prove nothing (the
        replica may be one accept-loop tick away), so the relative order
        within each class is preserved and nothing is removed — failover
        can still reach a not-ready replica if every ready one dies.
        Returns the new (host, port) order."""
        ready, warming, dark = [], [], []
        for host, port in self._walker.endpoints:
            try:
                with socket.create_connection(
                        (host, port), timeout=probe_timeout) as sock:
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    sock.settimeout(probe_timeout)
                    wire.send_frame(sock, wire.KIND_REQUEST,
                                    {"op": wire.OP_STATS, "req": 0,
                                     "ring": 0}, [])
                    while True:
                        kind, rhdr, _ = wire.read_frame(sock)
                        if kind == wire.KIND_REPLY and rhdr.get("req") == 0:
                            break
                (ready if rhdr.get("ready", True) else warming).append(
                    (host, port))
            except (ConnectionError, ProtocolError, socket.timeout,
                    OSError):
                dark.append((host, port))
        order = ready + warming + dark

        def teardown():
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

        with self._lock:
            self._walker.reorder(order, on_walk=teardown)
        return list(order)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
