"""Online serving plane: continuous micro-batching over the netps wire.

The training side of this repo reproduces dist-keras' distributed
optimizers; this package is the north-star's other half — "serving heavy
traffic": a request frontend on the hardened wire protocol with

* **continuous micro-batching** — concurrent requests coalesce up to a
  latency budget and pad to bucketed shapes so jit never retraces
  (``serving/batcher.py``, ``serving/model.py``);
* **admission control** — bounded queue, shed-before-accept, typed
  overload/deadline replies; an accepted request is never silently
  dropped (``serving/errors.py``);
* **hot-swap checkpoints** — a registry watches the trainer's checkpoint
  directory, sha256-verifies and warmup-probes each new step, and swaps
  atomically between batches (``serving/registry.py``);
* **HA replica sets** — N replicas as a first-class fleet tenant with a
  preemption floor; clients walk the endpoint list on failure
  (``serving/replica.py``, ``serving/frontend.py``).

See docs/SERVING.md for the batching model, the shed contract, and the
failure matrix.
"""

from distkeras_tpu.serving.batcher import (
    MicroBatcher,
    bucket_for,
    parse_buckets,
)
from distkeras_tpu.serving.errors import (
    DeadlineExceededError,
    ModelUnavailableError,
    OverloadedError,
    ServingError,
)
from distkeras_tpu.serving.frontend import ServeClient, ServingFrontend
from distkeras_tpu.serving.model import BucketedModel
from distkeras_tpu.serving.registry import ModelRegistry
from distkeras_tpu.serving.replica import ServingReplicaSet, ServingService

__all__ = [
    "BucketedModel",
    "DeadlineExceededError",
    "MicroBatcher",
    "ModelRegistry",
    "ModelUnavailableError",
    "OverloadedError",
    "ServeClient",
    "ServingError",
    "ServingFrontend",
    "ServingReplicaSet",
    "ServingService",
    "bucket_for",
    "parse_buckets",
]
