"""Multi-model hot-swap: serve version N while version N+1 proves itself.

The :class:`ModelRegistry` watches a checkpoint directory (the one the
trainer saves into) with the cheap manager-less scan from
``checkpoint.latest_step`` — the same newest-intact-first walk the
trainer's resume uses, so the two planes agree on which step is "the
latest good one". Each newer candidate step is restored through
``Checkpointer.restore(verify=True)`` (the sha256 digest sidecar vets the
payload), wrapped in a fresh :class:`~distkeras_tpu.serving.model.
BucketedModel`, and **warmup-probed** — all buckets compiled, outputs
finite — before it is swapped in. The swap itself is an atomic reference
replacement under the registry lock, taken by the frontend's dispatch
thread *between* batches: no batch ever sees half-old half-new weights,
and the old version keeps answering until the instant the new one is
proven.

A candidate that fails restore or probe is remembered and skipped
(``serving.swap_failures``); the registry falls back to the next-newest
candidate, mirroring ``Trainer._resume_from_checkpoint``'s corruption
fallback, and keeps serving the incumbent either way.

Two streaming-loop extensions:

* ``quality_gate`` — an optional ``gate(candidate, step) -> bool``
  called after the probe and before the swap (e.g.
  :meth:`DriftWatch.regression_gate`, which scores the candidate on
  held-out recent data). A refusal is **rollback-on-regression**: the
  step joins ``_failed`` (``serving.swap_rejected_regression``) and the
  incumbent keeps serving.
* **Freshness at swap**: when the candidate's checkpoint meta carries
  ``event_ts`` (the newest stream-event timestamp folded into those
  weights — the streaming trainer writes it), the registry records
  event-to-served-weight freshness (``serving.freshness`` histogram,
  ``serving.freshness_s`` gauge) at the swap instant — the
  close-the-loop metric the streaming bench reports.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from distkeras_tpu import checkpoint as ckpt_mod
from distkeras_tpu.runtime import config
from distkeras_tpu.serving.model import BucketedModel


class ModelRegistry:
    """Owns the live :class:`BucketedModel` + its version (checkpoint
    step; -1 = the build-time params, nothing restored yet) and the
    polling thread that hot-swaps newer verified checkpoints in."""

    def __init__(self, model, buckets, directory: Optional[str] = None,
                 poll_s: Optional[float] = None, warmup: bool = True,
                 quality_gate=None):
        self.directory = directory
        #: optional ``gate(candidate: BucketedModel, step) -> bool`` run
        #: after the warmup probe; False refuses the swap permanently.
        self.quality_gate = quality_gate
        self.poll_s = float(config.env_float("DKTPU_SERVE_POLL_S")
                            if poll_s is None else poll_s)
        self._model = model
        self.buckets = tuple(buckets)
        self._lock = threading.Lock()
        #: True while a warmup/swap probe is compiling — the not-ready
        #: window the frontend's stats op reports to the health plane.
        self.warming = True
        self._bucketed = BucketedModel(model, self.buckets)
        try:
            if warmup:
                self._bucketed.warmup()
        finally:
            self.warming = False
        self._version = -1
        self._failed: set[int] = set()
        self._ckpt = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- serving side -------------------------------------------------------

    def current(self) -> tuple[BucketedModel, int]:
        """The live (model, version) pair — one atomic read; the dispatch
        thread calls this per batch, so a swap lands cleanly between two
        batches and never inside one."""
        with self._lock:
            return self._bucketed, self._version

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def compiles(self) -> int:
        with self._lock:
            return self._bucketed.compiles()

    # -- watch side ---------------------------------------------------------

    def poll_once(self) -> bool:
        """One scan of the checkpoint directory; restores + probes + swaps
        the newest intact candidate newer than the live version. Returns
        whether a swap happened."""
        from distkeras_tpu import telemetry

        if self.directory is None:
            return False
        steps = ckpt_mod.scan_steps(self.directory)
        candidates = ckpt_mod.resume_candidates(
            steps, lambda s: ckpt_mod.read_meta(self.directory, s)
            is not None)
        for step in candidates:
            if step <= self._version or step in self._failed:
                continue
            try:
                self.warming = True
                try:
                    candidate = self._load_and_probe(step)
                finally:
                    self.warming = False
            except Exception as e:  # noqa: BLE001 - fall back to next step
                self._failed.add(step)
                telemetry.counter("serving.swap_failures").add(1)
                telemetry.event("serve_swap_failed", {
                    "step": step, "error": repr(e)})
                import warnings

                warnings.warn(
                    f"serving hot-swap candidate step {step} rejected "
                    f"({type(e).__name__}: {e}); still serving version "
                    f"{self._version}", stacklevel=2)
                continue
            if self.quality_gate is not None:
                try:
                    ok = bool(self.quality_gate(candidate, step))
                except Exception:  # noqa: BLE001 - a broken gate rejects
                    ok = False
                if not ok:
                    self._failed.add(step)
                    telemetry.counter(
                        "serving.swap_rejected_regression").add(1)
                    telemetry.event("serve_swap_rejected", {"step": step})
                    continue
            with self._lock:
                self._bucketed = candidate
                self._version = step
            telemetry.counter("serving.swaps").add(1)
            telemetry.event("serve_swap", {"step": step})
            self._note_freshness(step)
            return True
        return False

    def _note_freshness(self, step: int) -> None:
        """Event-to-served-weight freshness: now minus the newest stream
        event folded into the just-swapped weights (meta ``event_ts``,
        written by the streaming trainer; absent for batch checkpoints)."""
        from distkeras_tpu import telemetry

        meta = ckpt_mod.read_meta(self.directory, step) or {}
        event_ts = meta.get("event_ts")
        if event_ts is None:
            return
        fresh = max(0.0, time.time() - float(event_ts))
        telemetry.gauge("serving.freshness_s").set(round(fresh, 3))
        telemetry.histogram("serving.freshness").observe(fresh)
        telemetry.event("serve_freshness", {
            "step": step, "seconds": round(fresh, 3)})

    def _load_and_probe(self, step: int) -> BucketedModel:
        """Restore ``step`` (digest-verified) into the model's parameter
        structure and warmup-probe a fresh bucketed wrapper; any failure
        raises and the caller keeps the incumbent."""
        if self._ckpt is None:
            from distkeras_tpu.checkpoint import Checkpointer

            self._ckpt = Checkpointer(self.directory)
        params = self._ckpt.restore(
            self._model.params, step=step, verify=True)
        candidate = BucketedModel(
            self._model.with_params(params), self.buckets)
        candidate.warmup()  # the probe: compiles + finiteness, or raises
        return candidate

    def start(self) -> None:
        """Launch the polling thread (idempotent)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 - poller must survive
                    pass
                self._stop.wait(self.poll_s)

        self._thread = threading.Thread(
            target=_loop, name="serve-registry", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._ckpt is not None:
            try:
                self._ckpt.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            self._ckpt = None
