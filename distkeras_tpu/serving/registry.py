"""Multi-model hot-swap: serve version N while version N+1 proves itself.

The :class:`ModelRegistry` watches a checkpoint directory (the one the
trainer saves into) with the cheap manager-less scan from
``checkpoint.latest_step`` — the same newest-intact-first walk the
trainer's resume uses, so the two planes agree on which step is "the
latest good one". Each newer candidate step is restored through
``Checkpointer.restore(verify=True)`` (the sha256 digest sidecar vets the
payload), wrapped in a fresh :class:`~distkeras_tpu.serving.model.
BucketedModel`, and **warmup-probed** — all buckets compiled, outputs
finite — before it is swapped in. The swap itself is an atomic reference
replacement under the registry lock, taken by the frontend's dispatch
thread *between* batches: no batch ever sees half-old half-new weights,
and the old version keeps answering until the instant the new one is
proven.

A candidate that fails restore or probe is remembered and skipped
(``serving.swap_failures``); the registry falls back to the next-newest
candidate, mirroring ``Trainer._resume_from_checkpoint``'s corruption
fallback, and keeps serving the incumbent either way.
"""

from __future__ import annotations

import threading
from typing import Optional

from distkeras_tpu import checkpoint as ckpt_mod
from distkeras_tpu.runtime import config
from distkeras_tpu.serving.model import BucketedModel


class ModelRegistry:
    """Owns the live :class:`BucketedModel` + its version (checkpoint
    step; -1 = the build-time params, nothing restored yet) and the
    polling thread that hot-swaps newer verified checkpoints in."""

    def __init__(self, model, buckets, directory: Optional[str] = None,
                 poll_s: Optional[float] = None, warmup: bool = True):
        self.directory = directory
        self.poll_s = float(config.env_float("DKTPU_SERVE_POLL_S")
                            if poll_s is None else poll_s)
        self._model = model
        self.buckets = tuple(buckets)
        self._lock = threading.Lock()
        #: True while a warmup/swap probe is compiling — the not-ready
        #: window the frontend's stats op reports to the health plane.
        self.warming = True
        self._bucketed = BucketedModel(model, self.buckets)
        try:
            if warmup:
                self._bucketed.warmup()
        finally:
            self.warming = False
        self._version = -1
        self._failed: set[int] = set()
        self._ckpt = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- serving side -------------------------------------------------------

    def current(self) -> tuple[BucketedModel, int]:
        """The live (model, version) pair — one atomic read; the dispatch
        thread calls this per batch, so a swap lands cleanly between two
        batches and never inside one."""
        with self._lock:
            return self._bucketed, self._version

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def compiles(self) -> int:
        with self._lock:
            return self._bucketed.compiles()

    # -- watch side ---------------------------------------------------------

    def poll_once(self) -> bool:
        """One scan of the checkpoint directory; restores + probes + swaps
        the newest intact candidate newer than the live version. Returns
        whether a swap happened."""
        from distkeras_tpu import telemetry

        if self.directory is None:
            return False
        steps = ckpt_mod.scan_steps(self.directory)
        candidates = ckpt_mod.resume_candidates(
            steps, lambda s: ckpt_mod.read_meta(self.directory, s)
            is not None)
        for step in candidates:
            if step <= self._version or step in self._failed:
                continue
            try:
                self.warming = True
                try:
                    candidate = self._load_and_probe(step)
                finally:
                    self.warming = False
            except Exception as e:  # noqa: BLE001 - fall back to next step
                self._failed.add(step)
                telemetry.counter("serving.swap_failures").add(1)
                telemetry.event("serve_swap_failed", {
                    "step": step, "error": repr(e)})
                import warnings

                warnings.warn(
                    f"serving hot-swap candidate step {step} rejected "
                    f"({type(e).__name__}: {e}); still serving version "
                    f"{self._version}", stacklevel=2)
                continue
            with self._lock:
                self._bucketed = candidate
                self._version = step
            telemetry.counter("serving.swaps").add(1)
            telemetry.event("serve_swap", {"step": step})
            return True
        return False

    def _load_and_probe(self, step: int) -> BucketedModel:
        """Restore ``step`` (digest-verified) into the model's parameter
        structure and warmup-probe a fresh bucketed wrapper; any failure
        raises and the caller keeps the incumbent."""
        if self._ckpt is None:
            from distkeras_tpu.checkpoint import Checkpointer

            self._ckpt = Checkpointer(self.directory)
        params = self._ckpt.restore(
            self._model.params, step=step, verify=True)
        candidate = BucketedModel(
            self._model.with_params(params), self.buckets)
        candidate.warmup()  # the probe: compiles + finiteness, or raises
        return candidate

    def start(self) -> None:
        """Launch the polling thread (idempotent)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 - poller must survive
                    pass
                self._stop.wait(self.poll_s)

        self._thread = threading.Thread(
            target=_loop, name="serve-registry", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._ckpt is not None:
            try:
                self._ckpt.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            self._ckpt = None
