"""Typed failure taxonomy of the online serving plane.

Mirrors ``netps/errors.py``: every way an inference RPC can fail is one of
these, carried on the wire as a typed ``error`` kind in the reply header,
so clients and tests match on type — never on message strings. All of them
subclass :class:`~distkeras_tpu.resilience.errors.ResilienceError`; the
serving plane is part of the resilience surface.

The admission contract these types encode (docs/SERVING.md):

* a request the frontend cannot take is **shed before it is accepted** —
  :class:`OverloadedError` is the reply, and nothing of the request is
  queued;
* an **accepted** request is *never* silently dropped — it is answered
  with a result, or with :class:`DeadlineExceededError` (it aged past its
  deadline in the queue) or :class:`ModelUnavailableError` (the frontend
  shut down / has no warmed model) — a typed reply either way.
"""

from __future__ import annotations

from distkeras_tpu.resilience.errors import ResilienceError


class ServingError(ResilienceError):
    """Base class for every serving-plane failure."""


class OverloadedError(ServingError):
    """Admission control shed this request BEFORE accepting it: the queue
    bound (``DKTPU_SERVE_QUEUE`` rows) would be exceeded, or the request is
    larger than the largest batch bucket. Nothing was queued; retrying
    against another replica (or later) is safe and is what the client's
    endpoint walk does for load balancing."""


class DeadlineExceededError(ServingError):
    """An *accepted* request aged past ``DKTPU_SERVE_DEADLINE_MS`` while
    queued, so the frontend answered it with this instead of computing a
    result nobody is waiting for. Not silent — this IS the typed reply."""


class ModelUnavailableError(ServingError):
    """The frontend has no model to answer with: the registry holds
    nothing warmed yet, or the frontend is shutting down and is answering
    its queue out with typed replies rather than dropping it."""


#: wire ``error`` kinds <-> exception types (the reply-header vocabulary;
#: the client's inverse map lives in ``serving/frontend.py``).
ERROR_KINDS = {
    OverloadedError: "overloaded",
    DeadlineExceededError: "deadline",
    ModelUnavailableError: "unavailable",
}


def error_kind(exc: BaseException) -> str:
    """The wire kind for ``exc`` (``"serving"`` for the generic base)."""
    return ERROR_KINDS.get(type(exc), "serving")
