"""HA replica sets + the fleet-tenant face of the serving plane.

:class:`ServingReplicaSet` runs N :class:`~distkeras_tpu.serving.frontend.
ServingFrontend` replicas, each with its own registry watching the same
checkpoint directory (so a hot-swap rolls across the set as each poller
notices the new step) and its own bind-probed pool port. ``endpoints()``
renders the comma-separated form ``ServeClient``/``wire.split_endpoints``
walks — kill one replica and the client fails over to the survivors;
that is the whole HA story, exercised by ``tests/smoke_serving_chaos.py``.

:class:`ServingService` adapts a replica set to the fleet runtime duck
protocol (``fleet/job.py``), so serving registers as a first-class tenant
beside training jobs: submit it with ``FleetJob(kind="serving",
min_gang=R)`` and the scheduler's preemption floor keeps at least R
replicas alive — a serving job may be shrunk to its floor but never fully
drained (``FleetScheduler._preempt``), because tail latency is the
tenant's contract.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from distkeras_tpu.serving.batcher import parse_buckets
from distkeras_tpu.serving.frontend import ServingFrontend
from distkeras_tpu.serving.registry import ModelRegistry


class ServingReplicaSet:
    """N frontends over one model / one checkpoint directory."""

    def __init__(self, model, n: int = 2, buckets=None,
                 directory: Optional[str] = None, host: str = "127.0.0.1",
                 poll_s: Optional[float] = None,
                 max_wait_s: Optional[float] = None,
                 max_queue_rows: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 watch: bool = False):
        self.model = model
        self.buckets = parse_buckets() if buckets is None else tuple(buckets)
        self.directory = directory
        self._kw = dict(max_wait_s=max_wait_s,
                        max_queue_rows=max_queue_rows,
                        deadline_s=deadline_s)
        self.host = host
        self.poll_s = poll_s
        self.watch = watch
        self.replicas: list[Optional[ServingFrontend]] = [None] * int(n)
        self._lock = threading.Lock()

    def start(self) -> "ServingReplicaSet":
        for i in range(len(self.replicas)):
            self.start_replica(i)
        return self

    def start_replica(self, i: int) -> ServingFrontend:
        """(Re)start replica ``i``: fresh registry, fresh pool port —
        exactly what a crashed replica's supervisor would do. The new
        endpoint is filed with the health target registry (``serve<i>``)
        so a MetricsHub on this process scrapes the set automatically."""
        from distkeras_tpu.telemetry.health import register_target

        with self._lock:
            if self.replicas[i] is not None:
                return self.replicas[i]
            registry = ModelRegistry(self.model, self.buckets,
                                     directory=self.directory,
                                     poll_s=self.poll_s)
            if self.watch and self.directory is not None:
                registry.start()
            front = ServingFrontend(registry, host=self.host,
                                    **self._kw).start()
            self.replicas[i] = front
        register_target(front.endpoint, f"serve{i}")
        return front

    def kill(self, i: int) -> None:
        """Chaos: crash replica ``i`` (no drain, no typed replies). The
        health registration is left in place on purpose: a crash is
        exactly what the ``target_down`` sentinel exists to catch, and
        ``start_replica(i)`` re-files the name with the new endpoint."""
        with self._lock:
            front, self.replicas[i] = self.replicas[i], None
        if front is not None:
            front.kill()
            front.registry.close()

    def stop_replica(self, i: int) -> None:
        """Graceful: drain replica ``i``'s queue with typed replies (and
        un-file it from the health registry — a deliberate stop must not
        page as an outage)."""
        from distkeras_tpu.telemetry.health import unregister_target

        with self._lock:
            front, self.replicas[i] = self.replicas[i], None
        if front is not None:
            unregister_target(f"serve{i}")
            front.close()
            front.registry.close()

    def endpoints(self) -> str:
        """Comma-separated live endpoints — the ``ServeClient`` /
        ``wire.split_endpoints`` failover form."""
        live = [f.endpoint for f in self.replicas if f is not None]
        return ",".join(live)

    def served(self) -> int:
        return sum(f.served for f in self.replicas if f is not None)

    def close(self) -> None:
        for i in range(len(self.replicas)):
            self.stop_replica(i)


class ServingService:
    """Fleet-runtime adapter: each granted worker runs one replica.

    Duck protocol (``fleet/job.py``): ``ensure_started`` builds the
    replica set (no replicas yet); ``worker_main(i, should_run)`` starts
    replica ``i`` and parks until released, then drains it gracefully —
    a scheduler shrink removes a replica, the client walk covers the gap;
    ``progress()`` is cumulative requests served (so chaos ``preempt@R``
    indices advance with real load); ``done()`` is False until ``close``
    — serving has no natural end, the floor + never-drain rule is what
    keeps it running.
    """

    def __init__(self, model, buckets=None,
                 directory: Optional[str] = None, **kw):
        self._model = model
        self._buckets = buckets
        self._directory = directory
        self._kw = kw
        self.replica_set: Optional[ServingReplicaSet] = None
        self._served_closed = 0
        self._closed = False
        self._lock = threading.Lock()

    def ensure_started(self) -> None:
        with self._lock:
            if self.replica_set is None:
                self.replica_set = ServingReplicaSet(
                    self._model, n=0, buckets=self._buckets,
                    directory=self._directory, **self._kw)

    def worker_slots(self, n: int) -> None:
        """Scheduler resize hook: grow the replica slot table to ``n``."""
        with self._lock:
            rs = self.replica_set
            while rs is not None and len(rs.replicas) < n:
                rs.replicas.append(None)

    def worker_main(self, worker_id: int, should_run) -> None:
        self.worker_slots(worker_id + 1)
        self.replica_set.start_replica(worker_id)
        try:
            while should_run() and not self._closed:
                time.sleep(0.02)
        finally:
            self.replica_set.stop_replica(worker_id)

    def endpoints(self) -> str:
        return self.replica_set.endpoints() if self.replica_set else ""

    def progress(self) -> int:
        rs = self.replica_set
        return self._served_closed + (rs.served() if rs else 0)

    def done(self) -> bool:
        return self._closed

    def revoke(self, worker_id: int) -> None:
        rs = self.replica_set
        if rs is not None and worker_id < len(rs.replicas):
            rs.kill(worker_id)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.replica_set is not None:
            self._served_closed += self.replica_set.served()
            self.replica_set.close()
