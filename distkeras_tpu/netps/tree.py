"""N-level aggregation trees that survive the WAN.

PR 6's :class:`~distkeras_tpu.netps.hier.AggregatorServer` is one level:
host aggregators in front of the root. This module generalizes it into
the tree the fleet simulator already predicts (``sim/cluster.py``
``TreeTopology``, the ``region_partition`` scenario): a bottom-up
:class:`TreeSpec` — ``DKTPU_TREE_SPEC="host:8,pool:4,region:2"`` —
declares the levels, and every interior node is a first-class failure
domain:

* **Its own PR 7 lineage.** A :class:`TreeNode` with a ``state_dir``
  journals every *absorbed-but-unflushed* worker window (durable intent
  records, in absorb order — the node's own cursor, since its update
  counter mirrors the ROOT lineage), snapshots, fences by epoch, and
  cold-restarts deduping its children's retransmits. A warm
  region-local :class:`TreeStandby` tails that journal over the
  existing ``replicate`` stream, promotes on lease lapse (bumping the
  epoch, fencing the dead node, and **joining the root itself** so the
  subtree keeps flowing), and the children re-parent through the
  ordinary rejoin/renegotiation path — their endpoint list carries the
  standby, so the :class:`~distkeras_tpu.netps.endpoints.EndpointWalker`
  finds it without new machinery.

* **Per-link codecs, negotiated not configured.** Each uplink runs PR
  13's probe machinery at join (``netps/tuner/probe.py``): int8 +
  error-feedback typically wins the cross-region hop, f32 (or the shm
  ring) wins within a host — picked per link from measured round trips,
  never globally. A level may pin a codec in the spec
  (``region:2:int8``) to skip the probe.

* **Partition ride-through.** A black-holed uplink buffers up to
  ``DKTPU_TREE_BUFFER`` combined windows (each already durable in the
  node's journal); on heal the buffer drains *in order* behind one
  membership re-proof, so exactly-once holds end-to-end (root dedup +
  per-level journals — zero replayed windows). Past the bound the
  OLDEST windows degrade to **counted, typed drops**
  (``netps_tree_window_drop`` events naming the constituent (wid, seq)
  set) that the staleness rule absorbs — never a silent divergence, and
  never a deadlock on a dead uplink: a send either returns inside the
  client's retry budget or the window stays buffered.

* **Mid-run link demotion/promotion.** ``link_down@K:S`` /
  ``link_flap@K:S`` (``K = TreeSpec.link_key(level, group)``) are
  consumed by the node's own uplink transport — no chaos proxy can sit
  on every interior hop — and a persistent transport-failure streak
  demotes the link to plain TCP (the shm->TCP fallback pattern,
  per-link, dedup-preserving: the redial keeps the worker id and rides
  the join's ``last_seq`` resume); a healthy streak re-negotiates back
  up, probe and all.

Window conservation is the no-silent-loss contract, exported in every
``stats`` reply's ``tree`` block and as the ``netps.tree.silent_loss``
gauge (asserted 0 by the chaos smoke)::

    absorbed == forwarded_commits + lost_commits + dropped_commits
                + buffered_commits + open_commits
"""

from __future__ import annotations

import collections
import re
import threading
import time
from dataclasses import dataclass
from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu.netps import wire
from distkeras_tpu.netps.errors import NetPSError
from distkeras_tpu.netps.fold import counter_scalar
from distkeras_tpu.netps.hier import _FLUSH_INTERVAL_S, AggregatorServer
from distkeras_tpu.netps.shards import make_ps_client
from distkeras_tpu.netps.standby import StandbyServer
from distkeras_tpu.resilience import faults as _faults
from distkeras_tpu.runtime import config
from distkeras_tpu.telemetry import tracing


#: link-key stride: ``link_key = level * _LINK_STRIDE + group``. The
#: fault-plan grammar (``kind@at``) forces the key into one integer;
#: the stride bounds a level at 1000 groups — wider than any deployment
#: this repo models (the sim's 960-worker tree peaks at 120).
_LINK_STRIDE = 1000

#: consecutive successful flushes on a demoted uplink before it is
#: re-negotiated back up (transport + codec probe).
_PROMOTE_AFTER_OKS = 8

_LEVEL_NAME = re.compile(r"^[A-Za-z_][A-Za-z0-9_-]*$")


@dataclass(frozen=True)
class TreeLevel:
    """One interior level, bottom-up: its name, the fan-in of each node
    at this level, and an optional pinned uplink codec (``None`` = probe
    per link)."""

    name: str
    fanout: int
    codec: Optional[str] = None


@dataclass(frozen=True)
class TreeSpec:
    """The tree's shape, bottom-up (leaf-most level first) — the same
    orientation as the simulator's ``TreeTopology`` levels, so a live
    tree and its what-if twin are declared in one grammar.

    Grammar (``DKTPU_TREE_SPEC``)::

        level[,level...]     level := name:fanout[:codec]

    e.g. ``host:8,pool:4,region:2`` or ``host:4,region:2:int8``. Worker
    ``rank``'s level-k group is ``rank // prod(fanouts[:k+1])`` —
    contiguous assignment, identical to ``TreeTopology.group_of``.
    """

    levels: Tuple[TreeLevel, ...]

    def __post_init__(self):
        if not self.levels:
            raise ValueError("a TreeSpec needs at least one level")
        seen = set()
        for lvl in self.levels:
            if not _LEVEL_NAME.match(lvl.name):
                raise ValueError(f"bad tree level name {lvl.name!r}")
            if lvl.name in seen:
                raise ValueError(f"duplicate tree level {lvl.name!r}")
            seen.add(lvl.name)
            if int(lvl.fanout) < 1:
                raise ValueError(
                    f"level {lvl.name!r}: fanout must be >= 1, "
                    f"got {lvl.fanout}")
            if lvl.codec is not None and lvl.codec not in wire.CODECS:
                raise ValueError(
                    f"level {lvl.name!r}: unknown codec {lvl.codec!r}; "
                    f"known: {list(wire.CODECS)}")

    # ------------------------------------------------------------------
    @classmethod
    def parse(cls, spec: str) -> "TreeSpec":
        levels = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (2, 3):
                raise ValueError(
                    f"bad tree level {part!r}: expected name:fanout[:codec]")
            try:
                fanout = int(bits[1])
            except ValueError:
                raise ValueError(
                    f"bad tree level {part!r}: fanout must be an integer")
            levels.append(TreeLevel(bits[0], fanout,
                                    bits[2] if len(bits) == 3 else None))
        return cls(tuple(levels))

    @classmethod
    def from_env(cls) -> Optional["TreeSpec"]:
        spec = config.env_str("DKTPU_TREE_SPEC")
        return cls.parse(spec) if spec else None

    def render(self) -> str:
        return ",".join(
            f"{lvl.name}:{lvl.fanout}" + (f":{lvl.codec}" if lvl.codec
                                          else "")
            for lvl in self.levels)

    # ------------------------------------------------------------------
    @property
    def depth(self) -> int:
        return len(self.levels)

    def _stride(self, level: int) -> int:
        stride = 1
        for lvl in self.levels[:int(level) + 1]:
            stride *= int(lvl.fanout)
        return stride

    def group_of(self, rank: int, level: int) -> int:
        """Worker ``rank``'s group index at ``level`` (contiguous, the
        ``TreeTopology.group_of`` rule)."""
        return int(rank) // self._stride(level)

    def nodes_at(self, level: int, workers: int) -> int:
        """Interior node count at ``level`` for a ``workers``-wide tree."""
        stride = self._stride(level)
        return (int(workers) + stride - 1) // stride

    def parent_group(self, level: int, group: int) -> int:
        """The level+1 group a level-``level`` node flushes into."""
        if level + 1 >= self.depth:
            raise ValueError(f"level {level} is the top interior level")
        return int(group) // int(self.levels[level + 1].fanout)

    # ------------------------------------------------------------------
    @staticmethod
    def link_key(level: int, group: int) -> int:
        """The (level, group) uplink packed into the one integer the
        fault-plan grammar allows (``link_down@K:S``)."""
        level, group = int(level), int(group)
        if level < 0 or group < 0 or group >= _LINK_STRIDE:
            raise ValueError(
                f"tree link (level={level}, group={group}) outside the "
                f"key encoding (0 <= group < {_LINK_STRIDE})")
        return level * _LINK_STRIDE + group

    @staticmethod
    def split_link_key(key: int) -> Tuple[int, int]:
        key = int(key)
        return key // _LINK_STRIDE, key % _LINK_STRIDE


class _Window(NamedTuple):
    """One taken combined window, in flight or buffered: the decoded f32
    accumulator, its MIN pull counter, and the constituent evidence."""

    acc: list
    pulled: int
    count: int
    members: int
    traces: list
    pairs: list


class _TreeUplink:
    """The buffered, fault-aware uplink half of a tree node — mixed into
    :class:`TreeNode` (always) and :class:`TreeStandby` (armed at
    promotion). Assumes the host class provides the aggregator absorb
    state (``_acc*``, ``forwarded``/``absorbed``/``lost_*``) and a
    ``_flush_cv`` condition on the server lock."""

    # -- state ---------------------------------------------------------
    def _init_tree_state(self, *, level, group, spec, buffer_windows,
                         link_codec, probe_links, demote_after) -> None:
        self.level = int(level)
        self.group = int(group)
        self.spec: Optional[TreeSpec] = (TreeSpec.parse(spec)
                                         if isinstance(spec, str) else spec)
        self.link_key = TreeSpec.link_key(self.level, self.group)
        self.buffer_windows = int(
            buffer_windows if buffer_windows is not None
            else config.env_int("DKTPU_TREE_BUFFER"))
        if self.buffer_windows < 0:
            raise ValueError("buffer_windows must be >= 0")
        #: ride-through queue of taken-but-unlanded combined windows,
        #: oldest first (drain order IS absorb order).
        self._buffer: collections.deque = collections.deque()
        self._requested_link_codec = link_codec
        self._probe_links = bool(probe_links)
        #: the codec this uplink actually runs (pinned, probed, or the
        #: client's join-negotiated default).
        self.link_codec: Optional[str] = None
        self.dropped_windows = 0
        self.dropped_commits = 0
        self.demote_after = int(
            demote_after if demote_after is not None
            else config.env_int("DKTPU_TREE_DEMOTE_AFTER"))
        self._uplink_fails = 0
        self._uplink_oks = 0
        self._uplink_demoted = False
        self.link_demotions = 0
        self.link_promotions = 0
        self.link_downs = 0
        #: wall-clock deadline an injected link fault black-holes until.
        self._link_until = 0.0
        self._flap_at: Optional[float] = None
        self._flap_s = 0.0
        #: the uplink went dark since the last successful drain: heal
        #: re-proves membership before draining buffered windows.
        self._was_dark = False

    # -- link fault consumption ----------------------------------------
    def _set_link_down(self, now: float, seconds: float) -> None:
        from distkeras_tpu import telemetry

        self._link_until = max(self._link_until, now + float(seconds))
        self.link_downs += 1
        telemetry.counter("netps.tree.link_downs").add(1)
        telemetry.event("netps_tree_link_down", {
            "level": self.level, "group": self.group,
            "seconds": float(seconds)})

    def _link_blackholed(self, consume: bool = True) -> bool:
        """Whether this node's uplink is black-holed right now. With
        ``consume`` (the flush path), also fires ``link_down`` /
        ``link_flap`` faults keyed to this link — the tree transport is
        its own chaos proxy, because nothing else can sit on an interior
        hop."""
        now = time.monotonic()
        if consume:
            plan = _faults.active_net_plan()
            if plan is not None:
                arg = plan.fire("link_down", self.link_key)
                if arg is not None:
                    self._set_link_down(now, arg)
                arg = plan.fire("link_flap", self.link_key)
                if arg is not None:
                    # down S, up S, down S: the second outage arms here
                    # and fires when its time comes.
                    self._set_link_down(now, arg)
                    self._flap_s = float(arg)
                    self._flap_at = now + 2.0 * float(arg)
            if self._flap_at is not None and now >= self._flap_at:
                self._flap_at = None
                self._set_link_down(now, self._flap_s)
        down = now < self._link_until
        if down:
            self._was_dark = True
        return down

    # -- per-link codec ------------------------------------------------
    def _negotiate_link_codec(self) -> None:
        """Pick THIS link's codec: the spec's pinned codec if any, else
        PR 13's timed micro-A/B probe sweep (skipped when the peer lacks
        the ``tuner`` bit — ``probe_codecs`` returns empty and the
        join-negotiated default stands). Best-effort by design: a failed
        probe leaves a working f32 link, never a broken one."""
        from distkeras_tpu import telemetry

        up = self._up
        if up is None:
            return
        picked, how = None, "default"
        try:
            if self._requested_link_codec and hasattr(up, "retune"):
                up.retune(codec=self._requested_link_codec)
                picked, how = self._requested_link_codec, "pinned"
            elif self._probe_links and hasattr(up, "probe"):
                with self._lock:
                    template = ([a.copy() for a in self._center]
                                if self._center else [])
                if template:
                    from distkeras_tpu.netps.tuner.probe import (best_codec,
                                                                 probe_codecs)
                    results = probe_codecs(up, template)
                    picked = best_codec(results)
                    if results:
                        how = "probed"
                    if picked is not None and picked != up.codec:
                        up.retune(codec=picked)
        except (NetPSError, OSError, ValueError):
            picked = None
        self.link_codec = (picked if picked is not None
                           else getattr(up, "codec", None))
        telemetry.counter("netps.tree.codec_negotiations").add(1)
        telemetry.event("netps_tree_link_codec", {
            "level": self.level, "group": self.group,
            "codec": self.link_codec, "how": how})

    # -- uplink lifecycle ----------------------------------------------
    def _uplink_client_kw(self) -> dict:
        kw = dict(getattr(self, "_uplink_kw", None) or {})
        if self._requested_link_codec:
            kw.setdefault("compress", self._requested_link_codec)
        return kw

    def _ensure_uplink(self) -> bool:
        """Dial the upstream if this node has no live client (a standby
        promoted inside the partition that killed its primary). Failure
        is not an error: windows keep buffering, bounded and typed."""
        if self._up is not None:
            return True
        up = None
        try:
            with self._lock:
                init = ([a.copy() for a in self._center]
                        if self._center else [])
            up = make_ps_client(self.upstream, **self._uplink_client_kw())
            center, updates = up.join(init=init)
        except (NetPSError, OSError):
            if up is not None:
                up.close()
            return False
        with self._lock:
            self._up = up
            self._center = [np.array(a, np.float32) for a in center]
            self._updates = counter_scalar(updates)
        self._negotiate_link_codec()
        return True

    def _redial_uplink(self, transport: Optional[str]) -> bool:
        """Tear the uplink down and re-dial under ``transport`` (``None``
        = renegotiate everything), KEEPING the worker id: the join's
        ``last_seq`` resume preserves upstream dedup, so a window sent
        before the swap cannot double-fold after it."""
        old = self._up
        if old is None:
            return self._ensure_uplink()
        kw = self._uplink_client_kw()
        if self.link_codec:
            kw["compress"] = self.link_codec
        up = None
        try:
            up = make_ps_client(self.upstream, transport=transport,
                                worker_id=getattr(old, "worker_id", None),
                                **kw)
            center, updates = up.join()
        except (NetPSError, OSError, ValueError):
            if up is not None:
                up.close()
            return False
        with self._lock:
            self._up = up
            self._center = [np.array(a, np.float32) for a in center]
            self._updates = counter_scalar(updates)
        try:
            old.close()
        except (NetPSError, OSError):
            pass
        return True

    def demote_uplink(self) -> bool:
        """Per-link mid-run demotion to plain TCP (the shm->TCP fallback
        pattern applied to ONE link): called automatically after
        ``demote_after`` consecutive transport failures, or explicitly by
        an operator. No-op when already demoted."""
        from distkeras_tpu import telemetry

        if self._uplink_demoted or not self._redial_uplink("tcp"):
            return False
        self._uplink_demoted = True
        self._uplink_oks = 0
        self.link_demotions += 1
        telemetry.counter("netps.tree.link_demotions").add(1)
        telemetry.event("netps_tree_link_demoted", {
            "level": self.level, "group": self.group})
        return True

    def promote_uplink(self) -> bool:
        """Undo a demotion: re-dial with full negotiation (transport
        upgrade + codec probe). Fired automatically after a healthy
        streak on the demoted link."""
        from distkeras_tpu import telemetry

        if not self._uplink_demoted or not self._redial_uplink(None):
            return False
        self._uplink_demoted = False
        self.link_promotions += 1
        telemetry.counter("netps.tree.link_promotions").add(1)
        telemetry.event("netps_tree_link_promoted", {
            "level": self.level, "group": self.group})
        self._negotiate_link_codec()
        return True

    # -- the buffered flush --------------------------------------------
    def _send_window(self, win: _Window) -> str:
        """One upstream commit attempt: ``ok``, ``evicted`` (landed but
        discarded — the lease lapsed with it pending), or ``down`` (died
        in transport inside the client's bounded retry budget — the
        caller keeps the window buffered; this call can never hang a dead
        uplink)."""
        try:
            with tracing.trace_scope("hier.flush", count=win.count,
                                     level=self.level, group=self.group,
                                     links=win.traces[:16]):
                res = self._up.commit(win.acc, win.pulled)
        except (NetPSError, OSError):
            return "down"
        return "evicted" if res.evicted else "ok"

    def _resync(self) -> None:
        """Re-adopt the root-lineage center + counter (best-effort; a
        failure just waits for the next flush). The pull doubles as the
        membership re-proof on heal — the client's auto-rejoin restores
        a lapsed lease without consuming a commit seq."""
        try:
            center, updates = self._up.pull()
        except (NetPSError, OSError):
            return
        with self._lock:
            self._center = [np.asarray(a, np.float32) for a in center]
            self._updates = counter_scalar(updates)

    def _drop_windows(self, windows: Sequence[_Window]) -> None:
        """Typed, counted degradation past the buffer bound: name the
        constituents, bump the counters, and move on — the staleness rule
        absorbs the gap when the survivors land."""
        from distkeras_tpu import telemetry

        count = sum(w.count for w in windows)
        self.dropped_windows += len(windows)
        self.dropped_commits += count
        telemetry.counter("netps.tree.dropped_windows").add(len(windows))
        telemetry.counter("netps.tree.dropped_commits").add(count)
        pairs = [p for w in windows for p in w.pairs][:512]
        telemetry.event("netps_tree_window_drop", {
            "reason": "buffer_overflow", "level": self.level,
            "group": self.group, "windows": len(windows), "count": count,
            "constituents": [[int(a), int(b)] for a, b in pairs]})

    def _flush_once(self, force: bool) -> bool:
        """The aggregator flush, with ride-through: take the open window
        into the bounded buffer, then drain the buffer in order while the
        uplink cooperates. Every window ends in exactly one ledger
        column — forwarded, lost (typed), dropped (typed), or still
        buffered — so ``silent_loss`` stays 0 by construction."""
        from distkeras_tpu import telemetry

        dropped: list = []
        with self._lock:
            taken = self._take_acc_locked(force)
            if taken is not None:
                self._buffer.append(_Window(*taken))
            while len(self._buffer) > self.buffer_windows:
                dropped.append(self._buffer.popleft())
            pending = len(self._buffer)
        if dropped:
            self._drop_windows(dropped)
        if not pending:
            return taken is not None
        if self._up is None and not self._ensure_uplink():
            return True  # redial attempted; the bounded buffer holds
        if self._link_blackholed():
            telemetry.gauge("netps.tree.buffered_windows").set(
                float(pending))
            return True
        dark, self._was_dark = self._was_dark, False
        if dark:
            self._resync()
        sent = 0
        while True:
            with self._lock:
                win = self._buffer[0] if self._buffer else None
            if win is None:
                break
            outcome = self._send_window(win)
            if outcome == "down":
                self._was_dark = True
                self._uplink_fails += 1
                if (self.demote_after > 0
                        and self._uplink_fails >= self.demote_after
                        and not self._uplink_demoted):
                    self.demote_uplink()
                break
            self._uplink_fails = 0
            with self._lock:
                if self._buffer and self._buffer[0] is win:
                    self._buffer.popleft()
            if outcome == "evicted":
                self._lose_window(win.pairs, win.count)
            else:
                sent += 1
                self.forwarded += 1
                self.forwarded_commits += win.count
                telemetry.counter("netps.hier.combined_commits").add(1)
                telemetry.counter("netps.hier.worker_commits").add(win.count)
                telemetry.gauge("netps.hier.fan_in").set(float(win.members))
        if dark and sent:
            telemetry.counter("netps.tree.drained_windows").add(sent)
        with self._lock:
            telemetry.gauge("netps.tree.buffered_windows").set(
                float(len(self._buffer)))
        if sent:
            self._uplink_oks += sent
            if self._uplink_demoted and self._uplink_oks >= _PROMOTE_AFTER_OKS:
                self.promote_uplink()
            self._resync()
        return True

    def _flusher_loop(self) -> None:
        lease = (getattr(self._up, "lease_s", None)
                 or config.env_float("DKTPU_PS_LEASE"))
        wait_s = self.flush_interval
        if lease:
            wait_s = min(wait_s, max(0.001, float(lease) / 3.0))
        last_rpc = time.monotonic()
        while not self._stop.is_set():
            with self._flush_cv:
                self._flush_cv.wait(wait_s)
            if self._flush_once(force=False):
                last_rpc = time.monotonic()
            elif time.monotonic() - last_rpc > float(lease) / 3.0:
                # A black-holed link loses heartbeats too — the upstream
                # lease is ALLOWED to lapse during a partition; the heal
                # path re-proves membership before draining.
                if self._up is not None and not self._link_blackholed():
                    try:
                        self._up.heartbeat()
                    except (NetPSError, OSError):
                        pass
                last_rpc = time.monotonic()

    # -- observability -------------------------------------------------
    def tree_stats(self) -> dict:
        """The window-conservation ledger + link state, served in every
        ``stats`` reply (the chaos smoke asserts ``silent_loss == 0`` on
        it) and exported as the ``netps.tree.silent_loss`` gauge."""
        from distkeras_tpu import telemetry

        with self._lock:
            buffered_w = len(self._buffer)
            buffered_c = sum(w.count for w in self._buffer)
            open_c = self._acc_count
            silent = self.absorbed - (self.forwarded_commits
                                      + self.lost_commits
                                      + self.dropped_commits
                                      + buffered_c + open_c)
            out = {
                "level": self.level, "group": self.group,
                "link_key": self.link_key,
                "spec": self.spec.render() if self.spec else None,
                "absorbed": self.absorbed, "forwarded": self.forwarded,
                "forwarded_commits": self.forwarded_commits,
                "lost_windows": self.lost_windows,
                "lost_commits": self.lost_commits,
                "dropped_windows": self.dropped_windows,
                "dropped_commits": self.dropped_commits,
                "buffered_windows": buffered_w,
                "buffered_commits": buffered_c,
                "open_commits": open_c,
                "silent_loss": silent,
                "link_codec": self.link_codec,
                "link_down": time.monotonic() < self._link_until,
                "link_demoted": self._uplink_demoted,
                "link_demotions": self.link_demotions,
                "link_promotions": self.link_promotions,
                "link_downs": self.link_downs,
            }
        telemetry.gauge("netps.tree.silent_loss").set(float(silent))
        return out

    def _op_stats(self, header: dict) -> tuple:
        hdr, arrays = super()._op_stats(header)
        hdr["tree"] = self.tree_stats()
        return hdr, arrays

    def _op_replicate(self, header: dict) -> tuple:
        """Replicate replies ride the node's ROOT-lineage counter along
        (``root_u``): the journal stream itself advances by the absorb
        cursor, but a standby promoting inside a partition needs the last
        known root counter to serve its children on."""
        hdr, arrays = super()._op_replicate(header)
        with self._lock:
            hdr["root_u"] = int(self._updates)
        return hdr, arrays


class TreeNode(_TreeUplink, AggregatorServer):
    """One interior aggregator of an N-level tree (see module docstring).

    Everything an :class:`AggregatorServer` accepts applies; on top:
    ``level``/``group`` locate the node in ``spec`` (and key its uplink
    for ``link_down``/``link_flap``), ``state_dir`` arms the node's own
    PR 7 lineage, ``buffer_windows`` bounds partition ride-through, and
    ``link_codec``/``probe_links`` control the per-link codec pick.
    """

    def __init__(self, upstream: str, *, level: int = 0, group: int = 0,
                 spec=None, buffer_windows: Optional[int] = None,
                 link_codec: Optional[str] = None, probe_links: bool = True,
                 demote_after: Optional[int] = None,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None, **agg_kw):
        spec = TreeSpec.parse(spec) if isinstance(spec, str) else spec
        if link_codec is None and spec is not None and level < spec.depth:
            link_codec = spec.levels[int(level)].codec
        self._init_tree_state(level=level, group=group, spec=spec,
                              buffer_windows=buffer_windows,
                              link_codec=link_codec,
                              probe_links=probe_links,
                              demote_after=demote_after)
        self._uplink_kw = dict(timeout=timeout, retries=retries,
                               backoff=backoff)
        super().__init__(upstream, timeout=timeout, retries=retries,
                         backoff=backoff, **agg_kw)
        self._negotiate_link_codec()

    def _caps(self) -> dict:
        caps = super()._caps()
        caps["tree"] = {"level": self.level, "group": self.group,
                        "spec": self.spec.render() if self.spec else None}
        return caps

    def close(self) -> None:
        super().close()  # drain, stop, final (buffered) flush, leave
        with self._lock:
            leftovers = list(self._buffer)
            self._buffer.clear()
        for win in leftovers:
            # The uplink died with these windows buffered: typed losses,
            # the same ledger column a flat worker's dead commit lands in.
            self._lose_window(win.pairs, win.count)


class TreeStandby(_TreeUplink, StandbyServer):
    """The region-local warm standby of one :class:`TreeNode`.

    Until promotion it is a plain :class:`StandbyServer` tailing the
    node's absorb journal — except that replicated records update ONLY
    the dedup table/evidence/journal, never the center: they are
    absorbed worker deltas, and folding them into the adopted root
    center would double-count once the primary's flush lands upstream.

    Promotion takes over the whole failure domain: bump + persist the
    epoch, fence the dead node, join the ROOT as a fresh member (the
    dead node's unflushed windows died with it — the standard
    lost-window semantics one level up), adopt the root center +
    counter, and start absorbing/flushing exactly like the node it
    replaced. Children re-parent via their ordinary endpoint walk; their
    retransmits dedup against the replicated table. If the same
    partition severs the uplink, promotion still completes on the last
    replicated root counter (``root_u``) and the flusher redials while
    windows buffer — bounded, typed, never deadlocked.
    """

    # The absorb half is the aggregator's, verbatim — borrowed as plain
    # functions rather than inherited, because this class must remain a
    # StandbyServer (the AggregatorServer ctor dials upstream eagerly;
    # a warm standby is cheap by contract).
    _init_absorb_state = AggregatorServer._init_absorb_state
    _fold_locked = AggregatorServer._fold_locked
    _take_acc_locked = AggregatorServer._take_acc_locked
    _lose_window = AggregatorServer._lose_window
    _repl_cursor_locked = AggregatorServer._repl_cursor_locked
    set_fan_in = AggregatorServer.set_fan_in

    def __init__(self, primary_endpoint: str, *, upstream: str,
                 level: int = 0, group: int = 0, spec=None,
                 buffer_windows: Optional[int] = None,
                 link_codec: Optional[str] = None, probe_links: bool = True,
                 demote_after: Optional[int] = None,
                 fan_in: Optional[int] = None,
                 flush_interval: float = _FLUSH_INTERVAL_S,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None, **kw):
        spec = TreeSpec.parse(spec) if isinstance(spec, str) else spec
        if link_codec is None and spec is not None and level < spec.depth:
            link_codec = spec.levels[int(level)].codec
        self._init_tree_state(level=level, group=group, spec=spec,
                              buffer_windows=buffer_windows,
                              link_codec=link_codec,
                              probe_links=probe_links,
                              demote_after=demote_after)
        self._uplink_kw = dict(timeout=timeout, retries=retries,
                               backoff=backoff)
        super().__init__(primary_endpoint, **kw)
        self.upstream = upstream
        self.flush_interval = float(flush_interval)
        self.fan_in = fan_in
        self._up = None
        #: last root-lineage counter replicated from the primary (the
        #: ``root_u`` rider): the promotion seed when the uplink is dark.
        self._root_u = 0
        self._init_absorb_state()
        #: pre-promotion, the replication cursor mirrors the update
        #: counter (one slot per applied record); promotion freezes it
        #: and repoints the counter at the root lineage.
        self._absorbs = int(self._updates)
        self._flush_cv = threading.Condition(self._lock)
        self._flusher_thread: Optional[threading.Thread] = None

    def _caps(self) -> dict:
        caps = super()._caps()
        caps["tree"] = {"level": self.level, "group": self.group,
                        "spec": self.spec.render() if self.spec else None}
        return caps

    # -- replication: dedup-table tail, never a center fold ------------
    def _apply(self, rhdr: dict, rarrays: list) -> bool:
        ru = rhdr.get("root_u")
        if ru is not None:
            self._root_u = int(ru)
        caught_up = super()._apply(rhdr, rarrays)
        with self._lock:
            if not self.promoted:
                self._absorbs = int(self._updates)
        return caught_up

    def _apply_record_locked(self, rec: dict, delta: list) -> None:
        """One replicated absorb record (lock held): the dedup table, the
        evidence log, and this standby's own journal — NOT the center
        (see class docstring). The cursor (``_updates`` until promotion)
        advances exactly as the primary's absorb cursor did."""
        wid, seq, st = int(rec["wid"]), int(rec["seq"]), int(rec["st"])
        t0, p0 = time.time(), time.perf_counter()
        self.commit_log.append((wid, seq, st))
        self._last_seq[wid] = seq
        self._ever.add(wid)
        self._updates += 1
        self.commits_total = int(rec.get("n", self.commits_total + 1))
        self.epoch = max(self.epoch, int(rec.get("e", 0)))
        if self._store is not None:
            self._store.append(epoch=self.epoch, wid=wid, seq=seq,
                               staleness=st, updates=self._updates - 1,
                               commits_total=self.commits_total,
                               delta=delta)
            if self._store.due(self._updates):
                self._snapshot_locked()
        self._trim_log_locked(2 * self._log_keep)
        if rec.get("tr"):
            tracing.emit("commit.replicate",
                         tracing.TraceContext(str(rec["tr"]), ""),
                         t0, time.perf_counter() - p0, wid=wid, seq=seq)

    def _snapshot_locked(self) -> None:
        """The snapshot cursor indexes this standby's OWN journal ``u``
        fields: the replication tail (``_updates``) until promotion, the
        absorb cursor after it (promotion repoints ``_updates`` at the
        root lineage, exactly like a live tree node's)."""
        cursor = self._absorbs if self.promoted else self._updates
        self._store.snapshot(center=self._center, updates=cursor,
                             last_seq=self._last_seq, epoch=self.epoch,
                             commits_total=self.commits_total)
        self.snapshots_written += 1
        self._trim_log_locked(self._log_keep + 1)

    # -- promotion: take over the failure domain AND its uplink --------
    def _promote(self) -> None:
        from distkeras_tpu import telemetry

        up = None
        center = updates = None
        try:
            with self._lock:
                init = ([a.copy() for a in self._center]
                        if self._center else [])
            up = make_ps_client(self.upstream, **self._uplink_client_kw())
            center, updates = up.join(init=init)
        except (NetPSError, OSError):
            if up is not None:
                up.close()
            up = None
        with self._lock:
            self._absorbs = int(self._updates)  # freeze the repl cursor
            self.epoch += 1
            if up is not None:
                self._up = up
                self._center = [np.array(a, np.float32) for a in center]
                self._updates = counter_scalar(updates)
            else:
                # Partitioned promotion: serve children on the last
                # replicated root counter; the flusher redials.
                self._updates = int(self._root_u)
            self._not_primary = False
            if self._store is not None:
                self._store.write_epoch(self.epoch)
            epoch = self.epoch
            behind = self._center is None
            # Inside the lock: the first child commit this node accepts
            # must already see promoted=True (the snapshot-cursor switch).
            self.promoted = True
        telemetry.counter("netps.failover.promotions").add(1)
        telemetry.event("netps_promotion", {
            "epoch": epoch, "updates": self._updates,
            "replicated": self.replicated, "cold": behind,
            "tree": {"level": self.level, "group": self.group,
                     "uplink": up is not None}})
        if up is not None:
            self._negotiate_link_codec()
        t = threading.Thread(target=self._fence_loop, args=(epoch,),
                             name="netps-standby-fence")
        t.start()
        self._fence_thread = t
        # Joined in close() through the _flusher_thread attribute — an
        # indirection the static join-tracking cannot follow.
        t2 = threading.Thread(target=self._flusher_loop,  # dk: disable=DK203
                              name="netps-tree-flush")
        t2.start()
        self._flusher_thread = t2

    def close(self) -> None:
        super().close()  # drains, stops replicate/fence, joins handlers
        t = self._flusher_thread
        if t is not None:
            t.join()
            self._flush_once(force=True)
        if self._up is not None:
            try:
                self._up.leave()
            except (NetPSError, OSError):
                pass
            self._up.close()
        with self._lock:
            leftovers = list(self._buffer)
            self._buffer.clear()
        for win in leftovers:
            self._lose_window(win.pairs, win.count)


# ---------------------------------------------------------------------------
# In-process assembly (tests, the loopback parity run)
# ---------------------------------------------------------------------------

class TreeDeployment:
    """An in-process tree: every interior node live on loopback, leaf
    endpoints ready for workers. Built by :func:`build_tree`; close()
    tears the tree down bottom-up (children drain into parents)."""

    def __init__(self, spec: TreeSpec, nodes):
        self.spec = spec
        #: ``nodes[level][group] -> TreeNode`` (interior levels only).
        self.nodes = nodes

    def leaf_endpoint(self, rank: int) -> str:
        return self.nodes[0][self.spec.group_of(rank, 0)].endpoint

    def node(self, level: int, group: int) -> TreeNode:
        return self.nodes[level][group]

    def close(self) -> None:
        for level in range(len(self.nodes)):
            for node in self.nodes[level].values():
                node.close()


def build_tree(spec, root_endpoint: str, workers: int,
               host: str = "127.0.0.1",
               init: Optional[Sequence[np.ndarray]] = None,
               **node_kw) -> TreeDeployment:
    """Stand up every interior node of ``spec`` on loopback, top level
    first (each node's upstream must be listening before the node joins
    it). ``node_kw`` (discipline, lease_s, flush_interval, fan_in,
    buffer_windows, state_dir is NOT threaded — per-node state dirs are a
    launcher concern) applies to every node."""
    spec = TreeSpec.parse(spec) if isinstance(spec, str) else spec
    nodes: dict = {}
    try:
        for level in range(spec.depth - 1, -1, -1):
            nodes[level] = {}
            for group in range(spec.nodes_at(level, workers)):
                if level == spec.depth - 1:
                    upstream = root_endpoint
                else:
                    parent = spec.parent_group(level, group)
                    upstream = nodes[level + 1][parent].endpoint
                node = TreeNode(upstream, level=level, group=group,
                                spec=spec, host=host, port=0,
                                init=init if level == spec.depth - 1
                                else None,
                                **node_kw)
                node.start()
                nodes[level][group] = node
    except BaseException:
        for tier in nodes.values():
            for node in tier.values():
                node.close()
        raise
    return TreeDeployment(spec, {lvl: nodes[lvl] for lvl in sorted(nodes)})
