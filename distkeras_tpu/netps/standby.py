"""Warm-standby failover: a second PSServer tailing the primary's journal.

The reference's parameter server was a single point of total state loss.
:mod:`netps/state.py` fixes the *durability* half (a killed primary cold-
restarts from its state dir); this module fixes the *availability* half: a
:class:`StandbyServer` is a real :class:`~distkeras_tpu.netps.server.
PSServer` that

* **tails the primary's journal stream** over the existing wire protocol
  (``replicate`` request frames — advertised by the ``replication`` bit in
  :data:`~distkeras_tpu.netps.wire.CAPS`): each reply is a batch of folded
  commits in their **wire dtype** (int8/bf16 specs included), re-folded
  here through the ONE shared :func:`~distkeras_tpu.netps.fold.fold_delta`
  with the recorded staleness, in the recorded order — so the standby's
  center is the primary's center, bit for bit, at every replicated index.
  A fresh (or gapped, or behind-the-tail) standby gets one full state
  sync (``mode=snapshot``) and resumes incremental tailing from there.
  Until it promotes it serves nothing: every client op answers the typed
  ``not_primary`` and the hardened client walks its endpoint list onward.

* **promotes itself when the primary's lease lapses**: no successful
  replicate for ``promote_after`` seconds (default: the membership lease —
  the same silence budget workers get) means the primary is gone. The
  standby bumps the epoch past everything it ever replicated, persists the
  promotion (``epoch.json`` in its state dir, if it has one), starts
  serving, and **fences the old lineage**: a best-effort ``fence`` frame is
  retried at the old primary for a while, and — belt to that suspender —
  every join/commit reply now carries the new epoch, so a commit from the
  old lineage answers ``EpochFencedError`` (never folded) and a zombie
  ex-primary that sees a higher-epoch request fences *itself*. Zero
  stale-epoch folds, whichever message arrives first.

* keeps the replicated dedup table, so a worker whose commit was ACKed by
  the dead primary retransmits to the promoted standby and is answered
  ``duplicate=True`` — **exactly-once accounting rides through the
  failover**; a commit the primary folded but never replicated is simply
  lost with it (the client retransmits and it folds once, here).

The split-brain caveat (documented in docs/RESILIENCE.md's failure-model
matrix): promotion is lease-based, so a partition that separates the
standby from a *healthy* primary promotes a second lineage. The epoch
fence guarantees the center never mixes lineages — clients fold into
exactly one epoch and the other side's commits are rejected typed — but
which lineage survives is decided by which endpoints the clients can
reach, not by a quorum this two-node design does not have.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

import numpy as np

from distkeras_tpu.netps import wire
from distkeras_tpu.netps.errors import ProtocolError
from distkeras_tpu.netps.fold import decode_entry, fold_delta
from distkeras_tpu.netps.server import PSServer
from distkeras_tpu.telemetry import tracing



class StandbyServer(PSServer):
    """A warm standby of the primary at ``primary_endpoint``.

    Accepts everything a :class:`PSServer` does (``state_dir`` gives the
    standby its own durable store, so a promoted-then-killed standby cold-
    restarts fenced-forward); ``promote_after`` defaults to the membership
    lease. ``start()`` begins replication; :attr:`promoted` flips once the
    standby has taken over (and :attr:`epoch` then exceeds everything the
    old lineage ever served).
    """

    def __init__(self, primary_endpoint: str, *,
                 promote_after: Optional[float] = None,
                 rpc_timeout: Optional[float] = None, **kw):
        super().__init__(standby=True, **kw)
        self.primary_endpoint = primary_endpoint
        self.promote_after = float(promote_after if promote_after is not None
                                   else self.lease_s)
        #: per-replicate deadline: must resolve well inside the promotion
        #: budget or a hung primary would stall the lapse detection.
        self.rpc_timeout = float(rpc_timeout if rpc_timeout is not None
                                 else max(0.2, self.promote_after / 3.0))
        self.promoted = False
        #: replicated commits applied / full snapshot syncs taken.
        self.replicated = 0
        self.snapshot_syncs = 0
        #: the primary incarnation this standby's state descends from: a
        #: change means the primary restarted and may have LOST journal
        #: tail this standby already replicated — fold indices would line
        #: up again while the histories differ, so the only safe move is
        #: to discard local state and full-sync (primary is authoritative).
        self._primary_lineage: Optional[str] = None
        self._repl_thread: Optional[threading.Thread] = None
        self._fence_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "StandbyServer":
        if self._started:
            return self
        super().start()
        t = threading.Thread(target=self._replicate_loop,
                             name="netps-standby-replicate")
        t.start()
        self._repl_thread = t
        return self

    def close(self) -> None:
        self._stop.set()
        for t in (self._repl_thread, self._fence_thread):
            if t is not None:
                t.join()
        super().close()

    # ------------------------------------------------------------------
    def _replicate_loop(self) -> None:
        """Tail the primary until promotion (or close). A plain socket —
        not a PSClient — because the stream must arrive ``decode=False``:
        replicated deltas re-fold in their wire dtype, the same arithmetic
        the primary ran and the journal replay runs (bit-identical center
        is the contract, and a dequantize-then-fold would break it in the
        last ulp)."""
        from distkeras_tpu import telemetry

        sock: Optional[socket.socket] = None
        req = 0
        last_ok = time.monotonic()
        tick = max(0.02, min(self.promote_after / 4.0, 0.25))
        while not self._stop.is_set():
            caught_up = True
            try:
                if sock is None:
                    sock = socket.create_connection(
                        wire.split_endpoint(self.primary_endpoint),
                        timeout=self.rpc_timeout)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                req += 1
                sock.settimeout(self.rpc_timeout)
                wire.send_frame(sock, wire.KIND_REQUEST,
                                {"op": wire.OP_REPLICATE, "u": self._next_u(),
                                 "req": req}, [])
                rhdr, rarrays = self._recv_reply(sock, req)
                err = rhdr.get("error")
                if err in ("uninitialized",):
                    # The primary is alive, just has no center yet.
                    last_ok = time.monotonic()
                elif err:
                    # A typed rejection (not_primary: the primary itself
                    # was fenced; protocol: a pre-replication peer). The
                    # peer is alive — do not promote over it — but this
                    # link cannot replicate; keep probing.
                    telemetry.counter(
                        "netps.failover.replicate_rejected").add(1)
                    last_ok = time.monotonic()
                else:
                    caught_up = self._apply(rhdr, rarrays)
                    last_ok = time.monotonic()
            except (socket.timeout, ConnectionError, OSError,
                    ProtocolError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
            if time.monotonic() - last_ok > self.promote_after:
                self._promote()
                break
            if caught_up:
                self._stop.wait(tick)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _recv_reply(self, sock: socket.socket, req: int):
        """One matched reply, wire-dtype arrays (``decode=False``)."""
        while True:
            prefix = wire.recv_exact(sock, wire.PREFIX_SIZE)
            kind, _n, rhdr, rarrays = wire.finish_frame(sock, prefix,
                                                        decode=False)
            if kind != wire.KIND_REPLY:
                raise ProtocolError(f"expected a reply frame, got {kind}")
            if rhdr.get("req") == req:
                return rhdr, rarrays

    def _next_u(self) -> int:
        with self._lock:
            # Until a snapshot sync has armed the lineage token, ask for a
            # full sync even if we hold a center — a RESTARTED standby
            # recovered its own durable state but cannot know whether the
            # primary is still the incarnation that state descends from
            # (same fold index, possibly different history); incremental
            # tailing before the first sync would run with the divergence
            # guard dark.
            if self._center is None or self._primary_lineage is None:
                return -1
            return self._updates

    def _apply(self, rhdr: dict, rarrays: list) -> bool:
        """Apply one replicate reply; returns whether we are caught up
        (False = a full batch arrived, pull again immediately)."""
        from distkeras_tpu import telemetry

        applied = 0
        lineage = rhdr.get("lineage")
        with self._lock:
            self.epoch = max(self.epoch, int(rhdr.get("epoch", 0)))
            if (rhdr.get("mode") != "snapshot"
                    and self._primary_lineage is not None
                    and lineage != self._primary_lineage):
                # The primary restarted between replicates and our fold
                # index happens to line up with its recovered one — same
                # index, possibly different history (the bounded journal
                # writer's tail died with the old incarnation). Discard
                # and full-sync rather than fold a divergent record.
                self._center = None
                return False
            if rhdr.get("mode") == "snapshot":
                self._primary_lineage = lineage
                self._center = [np.array(decode_entry(e), np.float32)
                                for e in rarrays]
                self._updates = int(rhdr["updates"])
                self._last_seq = {int(k): int(v) for k, v in
                                  (rhdr.get("last_seq") or {}).items()}
                self._ever |= set(self._last_seq)
                self.commits_total = int(rhdr.get("commits_total",
                                                  self._updates))
                # Wholesale adoption: any commit-log entries predate this
                # sync's lineage (a lineage discard lands here) — they are
                # not evidence about the adopted history, and keeping them
                # could even drive _log_dropped negative.
                self.commit_log.clear()
                self._log_dropped = self.commits_total
                self.snapshot_syncs += 1
                if self._store is not None:
                    self._snapshot_locked()
                caught_up = True
            else:
                records = rhdr.get("records") or ()
                off = 0
                for rec in records:
                    k = int(rec["k"])
                    delta = rarrays[off:off + k]
                    off += k
                    if int(rec["u"]) != self._updates:
                        # A gap (should be unreachable: we asked for our
                        # exact index). Next pull requests a full sync.
                        self._center = None
                        break
                    self._apply_record_locked(rec, delta)
                    applied += 1
                caught_up = len(records) < 1 or int(
                    rhdr.get("updates", self._updates)) <= self._updates
        if applied:
            self.replicated += applied
            telemetry.counter("netps.failover.replicated_commits").add(
                applied)
        return caught_up

    def _apply_record_locked(self, rec: dict, delta: list) -> None:
        """One journal record onto the local center (lock held) — the same
        bookkeeping the primary's fold ran, including the standby's own
        journal so a promoted-then-restarted standby recovers."""
        wid, seq, st = int(rec["wid"]), int(rec["seq"]), int(rec["st"])
        t0, p0 = time.time(), time.perf_counter()
        fold_delta(self._center, delta, self.discipline, st)
        self.commit_log.append((wid, seq, st))
        self._last_seq[wid] = seq
        self._ever.add(wid)
        self._updates += 1
        self.commits_total = int(rec.get("n", self.commits_total + 1))
        self.epoch = max(self.epoch, int(rec.get("e", 0)))
        if self._store is not None:
            self._store.append(epoch=self.epoch, wid=wid, seq=seq,
                               staleness=st, updates=self._updates - 1,
                               commits_total=self.commits_total,
                               delta=delta)
            if self._store.due(self._updates):
                self._snapshot_locked()
        self._trim_log_locked(2 * self._log_keep)
        if rec.get("tr"):
            # The journal record carried the originating commit's trace id
            # (``tr``) across the replication stream: this span joins that
            # trace directly, closing the commit→standby leg of the
            # critical path. An empty parent is deliberate — the client's
            # span ids never cross the replicate link, only the trace does.
            tracing.emit("commit.replicate",
                         tracing.TraceContext(str(rec["tr"]), ""),
                         t0, time.perf_counter() - p0, wid=wid, seq=seq)

    # ------------------------------------------------------------------
    def _promote(self) -> None:
        """Take over: bump the epoch past everything replicated, persist
        it, start serving, and fence the old lineage best-effort."""
        from distkeras_tpu import telemetry

        with self._lock:
            self.epoch += 1
            self._not_primary = False
            if self._store is not None:
                self._store.write_epoch(self.epoch)
            epoch = self.epoch
            behind = self._center is None
        self.promoted = True
        telemetry.counter("netps.failover.promotions").add(1)
        telemetry.event("netps_promotion", {
            "epoch": epoch, "updates": self._updates,
            "replicated": self.replicated, "cold": behind})
        t = threading.Thread(target=self._fence_loop, args=(epoch,),
                             name="netps-standby-fence")
        t.start()
        self._fence_thread = t

    def _fence_loop(self, epoch: int) -> None:
        """Fence the old primary for as long as this server lives. The
        ex-primary may be dead (fencing a corpse is a no-op), mid-restart
        (the whole point: catch it the moment it answers — a `Job` cold
        restart can revive it MINUTES later, long after any bounded retry
        budget would have given up, and a fresh client's join carries no
        epoch for the passive check to catch), or reachable all along (a
        partition only we fell on the wrong side of — then IT refuses our
        fence typed, and we stop: we are the stale lineage there). A
        landed fence persists in the zombie's state dir, but a STORELESS
        zombie forgets it on restart — the periodic re-send re-fences it
        within one interval, which is why the loop never ends on success."""
        interval = max(0.1, self.promote_after)
        while not self._stop.is_set():
            try:
                with socket.create_connection(
                        wire.split_endpoint(self.primary_endpoint),
                        timeout=self.rpc_timeout) as sock:
                    wire.send_frame(sock, wire.KIND_REQUEST,
                                    {"op": wire.OP_FENCE, "epoch": epoch,
                                     "req": 1}, [])
                    sock.settimeout(self.rpc_timeout)
                    rhdr, _ = self._recv_reply(sock, 1)
                if rhdr.get("error"):
                    return  # typed refusal: the peer outranks this epoch
            except (socket.timeout, ConnectionError, OSError,
                    ProtocolError):
                pass
            self._stop.wait(interval)
