"""The remote worker loop: the reference's executor loop over the real wire.

Each logical worker is a host thread running ``pull -> K local steps ->
commit`` against a :class:`~distkeras_tpu.netps.server.PSServer` through
the hardened :class:`~distkeras_tpu.netps.client.PSClient` — the same
jitted window (:func:`distkeras_tpu.workers.make_local_loop`) the engines
compile, the same worker-side discipline normalization the raced twin
uses (``racelab.run_raced``), and the same server-side fold
(:mod:`distkeras_tpu.netps.fold`). Gradient compute releases the GIL, so
worker threads genuinely interleave; commit order is whatever the network
and the OS deliver — the reference's architecture, end to end.

Elastic membership in the loop: a worker that went silent past its lease
(injected via the ``evict@R:S`` net fault, or a real stall) finds itself
evicted at the next RPC; the client re-joins automatically, the worker
discards its stale window, re-adopts the freshly pulled center (the
reference's rejoining-worker semantics), and training continues — no
global restart, and the survivors never stopped.

Mutable model state (BatchNorm stats) stays per-worker and unsynced here —
the reference's socket server only ever carried parameters.

Worker identity: ids 0..W-1 are per-*trainer*. A restarted worker process
resumes safely (``join`` hands back the server's last folded seq), but two
hosts pointing ``run_remote`` at one server would collide on ids — give
each host a disjoint id range (or its own server) until multi-host id
assignment is plumbed through ``Job``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np

from distkeras_tpu.data.batching import BatchPlan, apply_round_transform
from distkeras_tpu.netps.client import PSClient
from distkeras_tpu.netps.fold import check_discipline
from distkeras_tpu.resilience import faults as _faults


def _leaves(tree) -> list:
    import jax

    return [np.asarray(a, np.float32) for a in jax.tree.leaves(tree)]


def _worker_round(plan: BatchPlan, r: int, w: int):
    """Worker ``w``'s ``[K, B, ...]`` slice of round ``r`` (each thread
    gathers only its own rows — the per-executor partition)."""
    idx = plan.index[r, w]
    xs, ys = plan.x[idx], plan.y[idx]
    if plan.transform is not None:
        xs4, ys4 = apply_round_transform(
            plan.transform, plan.transform_seed, r, [w],
            xs[None], ys[None])
        xs, ys = xs4[0], ys4[0]
    return xs, ys


def run_remote(
    *,
    endpoint: str,
    model,
    tx,
    loss_fn,
    plan: BatchPlan,
    discipline: str = "adag",
    window: int,
    alpha: float = 0.05,
    seed: int = 0,
    compute_dtype=None,
    grad_accum: int = 1,
    timeout: Optional[float] = None,
    retries: Optional[int] = None,
    backoff: Optional[float] = None,
) -> tuple[Any, np.ndarray]:
    """Train ``plan.num_workers`` threads against the PS at ``endpoint``.

    Returns ``(trained_params_tree, losses[rounds, W])`` — the params are
    the server's final center. Rows of ``losses`` for a round a worker's
    commit was discarded (eviction) still carry that worker's local loss;
    NaN marks rounds a worker never ran (it was asleep being evicted).

    The first joiner seeds an uninitialized server with this model's
    params, so a bare ``python -m distkeras_tpu.netps`` server needs no
    model knowledge.
    """
    import jax

    from distkeras_tpu import telemetry
    from distkeras_tpu.workers import make_local_loop

    check_discipline(discipline)
    W = plan.num_workers
    elastic = discipline in ("aeasgd", "eamsgd")
    treedef = jax.tree.structure(model.params)
    init_leaves = _leaves(model.params)
    loop_fn = jax.jit(make_local_loop(
        model.module, loss_fn, tx, compute_dtype=compute_dtype,
        state_collections=model.state_collections, grad_accum=grad_accum))
    losses = np.full((plan.num_rounds, W), np.nan, np.float32)
    errors: list = []
    base_key = jax.random.key(seed)

    def unflatten(leaves):
        return jax.tree.unflatten(treedef, [np.asarray(a) for a in leaves])

    def work(w: int) -> None:
        client = PSClient(endpoint, worker_id=w, timeout=timeout,
                          retries=retries, backoff=backoff)
        try:
            center_leaves, counter = client.join(init=init_leaves)
            params = unflatten(center_leaves)
            opt_state = tx.init(params)
            local = params if elastic else None
            mstate = (jax.tree.map(np.asarray, model.state)
                      if model.state is not None else None)
            readopt = False
            rejoins_seen = 0
            for r in range(plan.num_rounds):
                net = _faults.active_net_plan()
                if net is not None and net.poison_worker(r, W) == w:
                    arg = net.fire("evict", r)
                    if arg is not None:
                        # Go silent past the lease: the server evicts us;
                        # the next RPC re-joins and we continue.
                        lease = client.lease_s or 1.0
                        time.sleep(arg if arg > 0 else 2.0 * lease)
                pulled_leaves, counter = client.pull()
                if client.rejoin_count > rejoins_seen or readopt:
                    # Evicted while away: the rejoining worker re-adopts
                    # the center (fresh replica + optimizer — the
                    # reference's PS-pull join semantics).
                    rejoins_seen = client.rejoin_count
                    readopt = False
                    if elastic:
                        local = unflatten(pulled_leaves)
                        opt_state = tx.init(local)
                start = local if elastic else unflatten(pulled_leaves)
                xs, ys = _worker_round(plan, r, w)
                rng = jax.random.fold_in(jax.random.fold_in(base_key, w), r)
                new_params, opt_state, mstate, window_losses = loop_fn(
                    start, opt_state, xs, ys, rng, mstate)
                new_leaves = _leaves(new_params)
                pulled_np = [np.asarray(a, np.float32)
                             for a in pulled_leaves]
                if elastic:
                    e = [alpha * (n - p)
                         for n, p in zip(new_leaves, pulled_np)]
                    local = unflatten([n - d
                                       for n, d in zip(new_leaves, e)])
                    res = client.commit(e, counter)
                else:
                    delta = [n - p for n, p in zip(new_leaves, pulled_np)]
                    if discipline == "adag":
                        delta = [d / float(window) for d in delta]
                    res = client.commit(delta, counter)
                if res.evicted:
                    # The lease lapsed inside this window: the commit was
                    # discarded and the client already re-joined. Start
                    # over from the fresh center next round.
                    readopt = True
                losses[r, w] = float(np.mean(np.asarray(window_losses)))
            client.leave()
        except BaseException as e:  # noqa: BLE001 - surface on main thread
            errors.append(e)
        finally:
            client.close()

    with telemetry.span("netps.remote_train"):
        threads = [threading.Thread(target=work, args=(w,),
                                    name=f"netps-worker-{w}")
                   for w in range(W)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        raise errors[0]
    with PSClient(endpoint, timeout=timeout, retries=retries,
                  backoff=backoff) as observer:
        final_leaves, _updates = observer.pull()
    return unflatten(final_leaves), losses
