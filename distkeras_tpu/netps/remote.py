"""The remote worker loop: the reference's executor loop over the real wire.

Each logical worker is a host thread running ``pull -> K local steps ->
commit`` against a :class:`~distkeras_tpu.netps.server.PSServer` through
the hardened :class:`~distkeras_tpu.netps.client.PSClient` — the same
jitted window (:func:`distkeras_tpu.workers.make_local_loop`) the engines
compile, the same worker-side discipline normalization the raced twin
uses (``racelab.run_raced``), and the same server-side fold
(:mod:`distkeras_tpu.netps.fold`). Gradient compute releases the GIL, so
worker threads genuinely interleave; commit order is whatever the network
and the OS deliver — the reference's architecture, end to end.

**Compute/communication overlap** (``DKTPU_NET_INFLIGHT``): with the
default of 1 the loop is the serial PR 4 one — round *r*'s commit is
ACKed before round *r+1* begins. Raising it double-buffers the loop:
round *r*'s commit (and the next round's pull prefetch) run on background
comms threads while round *r+1*'s K jitted local steps execute, with at
most ``DKTPU_NET_INFLIGHT`` commits un-ACKed at any time. Commits still
leave in strict seq order (one ordered comms lane per worker), so the
exactly-once dedup story is untouched. The price is staleness: a
prefetched pull cannot contain the still-in-flight commits, so the
server's counter rule *naturally* charges the realized in-flight delay —
DynSGD's ``1/(staleness+1)`` scale and the staleness telemetry
(``netps.commit.staleness`` histogram + the ``discipline.staleness_*``
gauges the DisciplineMonitor exports) see the TRUE realized staleness,
not the serial loop's. The overlap's effectiveness is exported as the
``netps.overlap.hidden_fraction`` gauge (1 − visible comms wait / total
comms time).

Elastic membership in the loop: a worker that went silent past its lease
(injected via the ``evict@R:S`` net fault, or a real stall) finds itself
evicted at the next RPC; the client re-joins automatically, the worker
discards its stale window (including any in-flight commits — their
evicted results drain into a re-adopt), re-adopts the freshly pulled
center (the reference's rejoining-worker semantics), and training
continues — no global restart, and the survivors never stopped.

Mutable model state (BatchNorm stats) stays per-worker and unsynced here —
the reference's socket server only ever carried parameters.

Worker identity: ids 0..W-1 are per-*trainer*. A restarted worker process
resumes safely (``join`` hands back the server's last folded seq), but two
hosts pointing ``run_remote`` at one server would collide on ids — give
each host a disjoint id range (or its own server) until multi-host id
assignment is plumbed through ``Job``.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

import numpy as np

from distkeras_tpu.data.batching import BatchPlan, apply_round_transform
from distkeras_tpu.netps import wire
from distkeras_tpu.netps.client import CommitResult
from distkeras_tpu.netps.fold import check_discipline
from distkeras_tpu.netps.shards import (is_sharded_endpoint, make_ps_client,
                                        plan_for_model)
from distkeras_tpu.netps.tuner import Tuner, TunerState, autotune_enabled
from distkeras_tpu.resilience import faults as _faults
from distkeras_tpu.runtime import config


def _leaves(tree) -> list:
    import jax

    return [np.asarray(a, np.float32) for a in jax.tree.leaves(tree)]


def _leaf_names(tree) -> list:
    """Stable parameter names for partition rules: the pytree key path of
    each leaf, "/"-joined (``params/dense/kernel``-style for Flax trees)."""
    import jax

    def part(k):
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                return str(getattr(k, attr))
        return str(k).strip("[].'\"")

    paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    names = ["/".join(part(k) for k in path) or f"param_{i:04d}"
             for i, (path, _leaf) in enumerate(paths)]
    # Key paths are unique by construction, but a defensive fallback keeps
    # the plan's name->tensor contract total even for exotic pytrees.
    if len(set(names)) != len(names):
        names = [f"{n}#{i}" for i, n in enumerate(names)]
    return names


def _measured_opt_factor(tx, params) -> float:
    """Optimizer-state bytes per parameter byte, measured from the actual
    transform state (adagrad accumulators ~= 1.0; chained transforms more).
    This is what makes the shard plan budget center + OPTIMIZER memory —
    the per-shard cap is honest about what the shard really holds."""
    import jax

    center = sum(a.nbytes for a in _leaves(params))
    if center <= 0:
        return 0.0
    opt = sum(np.asarray(a).nbytes for a in jax.tree.leaves(tx.init(params)))
    return float(opt) / float(center)


def _worker_round(plan: BatchPlan, r: int, w: int):
    """Worker ``w``'s ``[K, B, ...]`` slice of round ``r`` (each thread
    gathers only its own rows — the per-executor partition)."""
    idx = plan.index[r, w]
    xs, ys = plan.x[idx], plan.y[idx]
    if plan.transform is not None:
        xs4, ys4 = apply_round_transform(
            plan.transform, plan.transform_seed, r, [w],
            xs[None], ys[None])
        xs, ys = xs4[0], ys4[0]
    return xs, ys


class _CommsMeter:
    """Run-wide comms accounting shared by the worker threads: total RPC
    busy time vs the wait the compute loop actually *saw*, plus the
    realized staleness of applied commits — the overlap evidence."""

    def __init__(self):
        self.lock = threading.Lock()
        self.busy = 0.0
        self.wait = 0.0
        self.stale = collections.deque(maxlen=256)

    def timed(self, fn, *args):
        """Run one RPC, charging its duration to ``busy`` (called on the
        comms threads)."""
        t0 = time.monotonic()
        try:
            return fn(*args)
        finally:
            with self.lock:
                self.busy += time.monotonic() - t0

    def blocking(self, fn, *args):
        """An RPC the compute thread itself waits through (round 0's pull,
        the serial loop): busy AND wait — nothing of it was hidden."""
        t0 = time.monotonic()
        try:
            return self.timed(fn, *args)
        finally:
            self.waited(time.monotonic() - t0)

    def waited(self, seconds: float) -> None:
        with self.lock:
            self.wait += seconds

    def commit_staleness(self, staleness: int) -> None:
        from distkeras_tpu import telemetry

        telemetry.histogram("netps.commit.staleness").observe(
            float(staleness))
        with self.lock:
            self.stale.append(int(staleness))
            vals = list(self.stale)
        # The same gauges DisciplineMonitor exports for in-process engines,
        # fed the REALIZED staleness the server charged (which includes any
        # in-flight overlap delay) instead of the analytic rotation.
        telemetry.gauge("discipline.staleness_mean").set(
            float(np.mean(vals)))
        telemetry.gauge("discipline.staleness_max").set(float(max(vals)))

    def export(self) -> None:
        from distkeras_tpu import telemetry

        with self.lock:
            busy, wait = self.busy, self.wait
        if busy > 0:
            telemetry.gauge("netps.overlap.hidden_fraction").set(
                round(max(0.0, min(1.0, 1.0 - wait / busy)), 4))


def run_remote(
    *,
    endpoint: str,
    model,
    tx,
    loss_fn,
    plan: BatchPlan,
    discipline: str = "adag",
    window: int,
    alpha: float = 0.05,
    seed: int = 0,
    compute_dtype=None,
    grad_accum: int = 1,
    timeout: Optional[float] = None,
    retries: Optional[int] = None,
    backoff: Optional[float] = None,
    inflight: Optional[int] = None,
    shards: Optional[int] = None,
    compress: Optional[str] = None,
    transport: Optional[str] = None,
    hier: Optional[bool] = None,
    hier_flush: Optional[float] = None,
    autotune: Optional[bool] = None,
    loop_fn=None,
) -> tuple[Any, np.ndarray]:
    """Train ``plan.num_workers`` threads against the PS at ``endpoint``.

    Returns ``(trained_params_tree, losses[rounds, W])`` — the params are
    the server's final center. Rows of ``losses`` for a round a worker's
    commit was discarded (eviction) still carry that worker's local loss;
    NaN marks rounds a worker never ran (it was asleep being evicted).

    ``inflight``/``shards``/``compress``/``transport``/``hier`` default
    from the registry (``DKTPU_NET_INFLIGHT``/``DKTPU_NET_SHARDS``/
    ``DKTPU_NET_COMPRESS``/``DKTPU_NET_TRANSPORT``/``DKTPU_NET_HIER``).

    With ``hier`` on, a per-host :class:`~distkeras_tpu.netps.hier.
    AggregatorServer` is interposed: the worker threads join IT (over the
    shm ring when negotiated — the local hop is exactly where the ring
    pays), it pre-combines their commits and forwards ONE combined commit
    per flush to the root at ``endpoint``, cutting root ingress by the
    worker fan-in. The trained params are still pulled from the ROOT.

    The first joiner seeds an uninitialized server with this model's
    params, so a bare ``python -m distkeras_tpu.netps`` server needs no
    model knowledge.

    With ``autotune`` on (``DKTPU_NET_AUTOTUNE``), a :class:`~distkeras_
    tpu.netps.tuner.controller.Tuner` closes the loop from the live
    gauges to the knobs: join-time codec probes, mid-run inflight/codec/
    striping retunes through :meth:`PSClient.retune`, and the HIER
    topology by the measured fan-in crossover. Knobs the caller (or the
    environment) pinned explicitly are respected as the starting point;
    the controller's guardrails are documented in ``netps/tuner/``.
    """
    import jax

    from distkeras_tpu import telemetry
    from distkeras_tpu.workers import make_local_loop

    check_discipline(discipline)
    W = plan.num_workers
    explicit_inflight = (inflight is not None
                         or config.env_is_set("DKTPU_NET_INFLIGHT"))
    inflight = max(1, int(inflight if inflight is not None
                          else config.env_int("DKTPU_NET_INFLIGHT")))
    autotune = (autotune_enabled() if autotune is None else bool(autotune))
    tuner = None
    if autotune:
        # Explicit knobs win where set; the controller fills the rest.
        # An unpinned inflight starts at 2 (the overlap window must exist
        # before hidden_fraction can be measured) and the control loop
        # walks it from there; an unpinned transport requests the TOP of
        # the demotion ladder (negotiated — a mesh request lands on the
        # device dispatch only against a same-runtime server, on the ring
        # for a same-host one, and cross-host pairs silently stay on TCP).
        tuner = Tuner(W, inflight=inflight if explicit_inflight
                      else max(inflight, 2))
        inflight = tuner.inflight
        if transport is None and not config.env_is_set("DKTPU_NET_TRANSPORT"):
            transport = "mesh"
        if (shards is None and not config.env_is_set("DKTPU_NET_SHARDS")
                and transport not in ("shm", "mesh")):
            # Striping headroom on TCP: connections are sized at
            # construction, so a client that might be retuned UP to 2
            # stripes mid-run needs 2 conns now (active stripes still
            # start join-negotiated). The ring never stripes, so it
            # keeps the single conn.
            shards = 2
    elastic = discipline in ("aeasgd", "eamsgd")
    treedef = jax.tree.structure(model.params)
    init_leaves = _leaves(model.params)
    if loop_fn is None:
        # Callers may pass a prebuilt jitted loop (bench.py A/Bs data-plane
        # variants against ONE compiled executable).
        loop_fn = jax.jit(make_local_loop(
            model.module, loss_fn, tx, compute_dtype=compute_dtype,
            state_collections=model.state_collections, grad_accum=grad_accum,
            normalize_uint8=getattr(model, "normalize_uint8", True)))
    losses = np.full((plan.num_rounds, W), np.nan, np.float32)
    errors: list = []
    base_key = jax.random.key(seed)
    meter = _CommsMeter()
    client_kw = dict(timeout=timeout, retries=retries, backoff=backoff,
                     shards=shards, compress=compress, transport=transport)
    shard_plan = None
    if is_sharded_endpoint(endpoint):
        # Sharded center plane: build THE partition plan once, here, from
        # the model's leaves (names = pytree key paths, so env rules can
        # pin by layer) and the MEASURED optimizer-state factor — every
        # worker client carries it, and the servers hash-validate it at
        # join so plan drift is a typed error, never a silent mis-fold.
        shard_plan = plan_for_model(
            init_leaves, len(wire.split_shard_endpoints(endpoint)),
            names=_leaf_names(model.params),
            opt_factor=_measured_opt_factor(tx, model.params))
        telemetry.event("netps_shard_plan", {
            "shards": shard_plan.num_shards,
            "hash": shard_plan.plan_hash[:12],
            "skew": round(shard_plan.skew(), 4)})
        client_kw["plan"] = shard_plan
    hier = (config.env_bool("DKTPU_NET_HIER") if hier is None else bool(hier))
    if (tuner is not None and not hier
            and not config.env_is_set("DKTPU_NET_HIER")):
        # Nobody pinned the topology: pick it from the measured fan-in
        # crossover (the bench hier_curve's break-even) — hierarchical
        # combining only pays once this host's worker fan-in covers the
        # aggregator's window cost.
        hier = tuner.choose_topology() == "hier"
    agg = None
    worker_endpoint = endpoint
    if hier:
        from distkeras_tpu.netps.hier import AggregatorServer

        # The aggregator seeds the root (joining with this model's params)
        # and serves the local workers — over the shm ring when negotiated.
        agg_kw = {} if hier_flush is None else {"flush_interval": hier_flush}
        agg = AggregatorServer(
            upstream=endpoint, init=init_leaves, discipline=discipline,
            transport=transport, timeout=timeout, retries=retries,
            backoff=backoff, **agg_kw).start()
        worker_endpoint = agg.endpoint
        if tuner is not None:
            tuner.attach_aggregator(agg)

    def unflatten(leaves):
        return jax.tree.unflatten(treedef, [np.asarray(a) for a in leaves])

    def work(w: int) -> None:
        # The factory: a ShardedPSClient when worker_endpoint is a shard
        # matrix, a plain PSClient otherwise (the hier path always hands
        # workers the aggregator's plain endpoint — the aggregator's own
        # upstream client is the sharded one).
        client = make_ps_client(worker_endpoint, worker_id=w, **client_kw)
        pull_client = None
        commit_lane = pull_lane = None
        # With the tuner aboard the lanes always exist — the controller
        # may widen a serial (inflight=1) start into an overlapped one
        # mid-run, and lanes cannot be conjured from inside the loop.
        overlap = inflight > 1 or tuner is not None
        if overlap:
            # Two comms lanes per worker: an ORDERED commit lane (seq order
            # is the exactly-once contract) and a pull-prefetch lane on its
            # own client/connections, so a slow commit cannot serialize the
            # next round's pull behind it.
            commit_lane = ThreadPoolExecutor(
                1, thread_name_prefix=f"netps-commit-{w}")
            pull_lane = ThreadPoolExecutor(
                1, thread_name_prefix=f"netps-pull-{w}")
        try:
            center_leaves, counter = client.join(init=init_leaves)
            if tuner is not None and w == 0:
                # The join-time micro A/B (one worker probes; the winner
                # is published to everyone through the target generation).
                tuner.startup(client, center_leaves)
            tstate = TunerState()
            if overlap:
                pull_client = make_ps_client(worker_endpoint,
                                             worker_id=client.worker_id,
                                             **client_kw)
                # Striping/codec/transport state without a join: adopt the
                # negotiated dialect (membership is by worker_id, not by
                # connection).
                pull_client.adopt_dialect(client, center_leaves)
            params = unflatten(center_leaves)
            opt_state = tx.init(params)
            local = params if elastic else None
            mstate = (jax.tree.map(np.asarray, model.state)
                      if model.state is not None else None)
            readopt = False
            rejoins_seen = 0
            pending: collections.deque = collections.deque()
            next_pull = None

            def rejoins() -> int:
                n = client.rejoin_count
                if pull_client is not None:
                    n += pull_client.rejoin_count
                return n

            def guarded_commit(delta, counter, epoch):
                # Ordered-lane lineage guard: a commit queued BEFORE an
                # eviction-triggered rejoin (its delta was computed from
                # the pre-eviction pull lineage) must be discarded, not
                # folded into the fresh center — the same "discard the
                # stale window" semantics the serial loop gets for free.
                # The lane is ordered, so by the time this runs any rejoin
                # caused by an earlier queued commit is already counted.
                if rejoins() != epoch:
                    return CommitResult(applied=False, duplicate=False,
                                        evicted=True, updates=-1,
                                        staleness=-1)
                return client.commit(delta, counter)

            def drain_one() -> None:
                nonlocal readopt
                _r, fut = pending.popleft()
                t0 = time.monotonic()
                res = fut.result()
                meter.waited(time.monotonic() - t0)
                if res.evicted:
                    # The lease lapsed with this commit in flight: it was
                    # discarded and the client already re-joined. Start
                    # over from the fresh center at the next pull.
                    readopt = True
                elif res.applied:
                    meter.commit_staleness(res.staleness)

            for r in range(plan.num_rounds):
                net = _faults.active_net_plan()
                if net is not None and net.poison_worker(r, W) == w:
                    arg = net.fire("evict", r)
                    if arg is not None:
                        # Go silent past the lease: the server evicts us;
                        # the next RPC re-joins and we continue.
                        lease = client.lease_s or 1.0
                        time.sleep(arg if arg > 0 else 2.0 * lease)
                if next_pull is not None:
                    t0 = time.monotonic()
                    pulled_leaves, counter = next_pull.result()
                    meter.waited(time.monotonic() - t0)
                    next_pull = None
                else:
                    pulled_leaves, counter = meter.blocking(client.pull)
                if rejoins() > rejoins_seen or readopt:
                    # Evicted while away: the rejoining worker re-adopts
                    # the center (fresh replica + optimizer — the
                    # reference's PS-pull join semantics).
                    rejoins_seen = rejoins()
                    readopt = False
                    if elastic:
                        local = unflatten(pulled_leaves)
                        opt_state = tx.init(local)
                if tuner is not None:
                    if w == 0:
                        # Keep the overlap gauge live so the control loop
                        # reads this run's evidence, not a stale export.
                        meter.export()
                        tuner.maybe_decide(r, client.active_transport)
                    if tuner.generation != tstate.generation:
                        # Quiesce the ordered lane before touching the
                        # dialect: one logical commit finishes under ONE
                        # codec/striping (exactly-once needs nothing more
                        # — a retransmit keeps its seq either way).
                        while pending:
                            drain_one()
                        changed = tuner.apply_to(client, pulled_leaves,
                                                 tstate)
                        if changed and pull_client is not None:
                            pull_client.adopt_dialect(client, pulled_leaves)
                start = local if elastic else unflatten(pulled_leaves)
                xs, ys = _worker_round(plan, r, w)
                rng = jax.random.fold_in(jax.random.fold_in(base_key, w), r)
                new_params, opt_state, mstate, window_losses = loop_fn(
                    start, opt_state, xs, ys, rng, mstate)
                new_leaves = _leaves(new_params)
                pulled_np = [np.asarray(a, np.float32)
                             for a in pulled_leaves]
                if elastic:
                    e = [alpha * (n - p)
                         for n, p in zip(new_leaves, pulled_np)]
                    local = unflatten([n - d
                                       for n, d in zip(new_leaves, e)])
                    delta = e
                else:
                    delta = [n - p for n, p in zip(new_leaves, pulled_np)]
                    if discipline == "adag":
                        delta = [d / float(window) for d in delta]
                if commit_lane is not None:
                    # The tuner retargets the window mid-run; a narrowed
                    # window simply drains deeper before the next submit.
                    bound = tuner.inflight if tuner is not None else inflight
                    while len(pending) >= max(1, bound):
                        drain_one()
                    fut = commit_lane.submit(
                        meter.timed, guarded_commit, delta, counter,
                        rejoins())
                    pending.append((r, fut))
                    if r + 1 < plan.num_rounds:
                        next_pull = pull_lane.submit(
                            meter.timed, pull_client.pull)
                else:
                    res = meter.blocking(client.commit, delta, counter)
                    if res.evicted:
                        readopt = True
                    elif res.applied:
                        meter.commit_staleness(res.staleness)
                losses[r, w] = float(np.mean(np.asarray(window_losses)))
            while pending:
                drain_one()
            if tuner is not None and w == 0:
                # The converged dialect + decision counts, for the report
                # and the bench's auto arm (read from the event stream).
                tuner.export_summary(client)
            client.leave()
        except BaseException as e:  # noqa: BLE001 - surface on main thread
            errors.append(e)
        finally:
            if commit_lane is not None:
                commit_lane.shutdown(wait=True)
            if pull_lane is not None:
                pull_lane.shutdown(wait=True)
            if pull_client is not None:
                pull_client.close()
            client.close()

    try:
        with telemetry.span("netps.remote_train"):
            threads = [threading.Thread(target=work, args=(w,),
                                        name=f"netps-worker-{w}")
                       for w in range(W)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    finally:
        if agg is not None:
            # Flushes any half-accumulated combined commit upstream before
            # the final pull below reads the root's center.
            agg.close()
    if inflight > 1 or tuner is not None:
        # The gauge is OVERLAP evidence; the serial loop hides nothing by
        # construction, so exporting there would just report its absence.
        meter.export()
    if errors:
        raise errors[0]
    with make_ps_client(endpoint, plan=shard_plan, timeout=timeout,
                        retries=retries, backoff=backoff,
                        transport=transport) as observer:
        final_leaves, _updates = observer.pull()
    return unflatten(final_leaves), losses
