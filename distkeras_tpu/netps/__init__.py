"""netps — the networked parameter server, hardened.

The reference's defining artifact (``DeltaParameterServer`` /
``ADAGParameterServer``: a socket server, one handler thread per worker,
``with lock: fold(delta)``) rebuilt over a real network boundary with the
production edges the reference never had:

* :mod:`~distkeras_tpu.netps.wire` — length-prefixed, crc-checksummed
  binary frames with magic/version/size checks and request-id echo;
  zero-copy on both directions (``sendmsg`` scatter-gather out,
  ``recv_into`` one-buffer in) plus the capability-negotiated per-tensor
  delta codecs (``DKTPU_NET_COMPRESS=bf16|int8``);
* :mod:`~distkeras_tpu.netps.server` — :class:`PSServer`: one handler
  thread per connection, idempotent ``(worker_id, seq)`` commits,
  lease-based elastic membership (eviction + mid-run rejoin), graceful
  drain;
* :mod:`~distkeras_tpu.netps.client` — :class:`PSClient`: deadline per
  RPC (``DKTPU_NET_TIMEOUT``), bounded retries with full-jitter backoff
  (``DKTPU_NET_RETRIES``/``DKTPU_NET_BACKOFF``), reconnect-on-failure,
  automatic rejoin after eviction;
* :mod:`~distkeras_tpu.netps.chaos` — :class:`ChaosProxy`: frame-aware
  delay/drop/dup/truncate/partition injection per direction, driven by
  ``DKTPU_NET_FAULTS`` through ``resilience.FaultPlan``;
* :mod:`~distkeras_tpu.netps.fold` — the ONE server-side fold shared with
  the in-process raced twin (``racelab``), so raced-parity evidence
  transfers to the network server by construction;
* :mod:`~distkeras_tpu.netps.remote` — the worker loop the async trainers
  run under ``remote="host:port"`` (pull -> K jitted local steps ->
  commit), double-buffered under ``DKTPU_NET_INFLIGHT`` so commits and
  pull prefetches overlap the next window's compute;
* :mod:`~distkeras_tpu.netps.shm` — the same-host fast path
  (``DKTPU_NET_TRANSPORT=shm``): payloads in an mmap'd seqlock'd ring,
  doorbell + fd-passing on a Unix socket, negotiated through the caps
  handshake with a boot-id check (cross-host/old peers stay on TCP);
* :mod:`~distkeras_tpu.netps.hier` — hierarchical two-level folds
  (``DKTPU_NET_HIER=1``): :class:`AggregatorServer` pre-combines a host's
  commits and forwards one combined commit upstream, cutting root ingress
  by the worker fan-in;
* :mod:`~distkeras_tpu.netps.state` — durable center state
  (``--state-dir`` / ``DKTPU_PS_STATE_DIR``): a write-ahead journal of
  folded commits plus periodic snapshots with sha256 sidecars; a killed
  server cold-restarts with the center, counter, and dedup table intact
  and in-flight commits retransmit exactly-once;
* :mod:`~distkeras_tpu.netps.standby` — warm-standby failover
  (``--standby`` / ``DKTPU_PS_STANDBY``): :class:`StandbyServer` tails
  the primary's journal stream over the wire, promotes itself when the
  primary's lease lapses, and fences the old epoch — stale-lineage
  commits answer a typed ``EpochFencedError``, never a fold; clients
  walk a comma-separated ``DKTPU_PS_ENDPOINT`` list to the promoted
  primary and reconcile seq state on re-join;
* :mod:`~distkeras_tpu.netps.endpoints` — the shared failover mechanics
  (split order, CAS walk, promotion patience window) every wire client
  rides: PSClient, the serving frontend, and the sharded fan-out;
* :mod:`~distkeras_tpu.netps.shards` — the sharded center plane: a
  :class:`PartitionPlan` (regex rules + byte-balanced default, budgeting
  optimizer state, row-splitting oversized tensors) assigns every tensor
  slice to one of N shard servers — each a full PSServer with its own
  journal lineage, warm standby, and epoch fence — and a
  :class:`ShardedPSClient` fans pulls/commits out under ONE logical seq,
  plan-hash-validated at join and on every pull (mismatch = typed
  :class:`ShardPlanError`, never a silent mis-fold). docs/SHARDING.md;
* :mod:`~distkeras_tpu.netps.tree` — N-level aggregation trees that
  survive the WAN (``DKTPU_TREE_SPEC=host:8,pool:4,region:2``): every
  interior :class:`TreeNode` is a first-class failure domain with its
  own journal lineage, epoch fence, and region-local warm
  :class:`TreeStandby` (promotes on lease lapse, re-parents the
  children, joins the root itself); per-link capability-negotiated
  codecs via the tuner's probe; partition ride-through — a black-holed
  uplink buffers up to ``DKTPU_TREE_BUFFER`` windows and degrades past
  the bound by counted, typed drops, never a silent divergence, never a
  deadlock on a dead uplink. docs/RESILIENCE.md;
* :mod:`~distkeras_tpu.netps.tuner` — the self-tuning data plane
  (``DKTPU_NET_AUTOTUNE=1``): join-time codec micro-probes over the
  negotiated connection plus an online controller that re-reads the live
  gauges and retunes compression/in-flight/striping/HIER fan-in mid-run
  through the SAME renegotiation paths a rejoin uses — guardrailed
  (floors never crossed, bounded retune rate, oscillation falls back to
  static, failover defers) and capability-gated so old peers see zero
  new traffic. docs/PERFORMANCE.md "Self-tuning data plane".

The data plane (compute/comms overlap, compressed deltas, sharded
striping over ``DKTPU_NET_SHARDS`` connections, zero-copy frames) is
documented in docs/PERFORMANCE.md "The netps data plane"; every knob is
off by default and negotiated at join, so PR 4 peers interoperate.

Run a standalone server with ``python -m distkeras_tpu.netps``; docs in
docs/RESILIENCE.md ("Network faults & elastic membership").
"""

from __future__ import annotations

from distkeras_tpu.netps.chaos import ChaosProxy  # noqa: F401
from distkeras_tpu.netps.client import CommitResult, PSClient  # noqa: F401
from distkeras_tpu.netps.errors import (  # noqa: F401
    EpochFencedError,
    LeaseExpiredError,
    NetPSError,
    NotPrimaryError,
    ProtocolError,
    RPCTimeoutError,
    ServerClosedError,
    ServerDrainingError,
    ShardPlanError,
)
from distkeras_tpu.netps.fold import (  # noqa: F401
    SUPPORTED_DISCIPLINES,
    commit_scale,
    fold_delta,
)
from distkeras_tpu.netps.hier import AggregatorServer  # noqa: F401
from distkeras_tpu.netps.server import PSServer, serve  # noqa: F401
from distkeras_tpu.netps.shards import (  # noqa: F401
    PartitionPlan,
    ShardedPSClient,
    ShardSet,
    make_ps_client,
)
from distkeras_tpu.netps.standby import StandbyServer  # noqa: F401
from distkeras_tpu.netps.tree import (  # noqa: F401
    TreeDeployment,
    TreeNode,
    TreeSpec,
    TreeStandby,
    build_tree,
)

__all__ = [
    "PSServer", "serve", "PSClient", "CommitResult", "ChaosProxy",
    "AggregatorServer", "StandbyServer",
    "TreeSpec", "TreeNode", "TreeStandby", "TreeDeployment", "build_tree",
    "PartitionPlan", "ShardedPSClient", "ShardSet", "make_ps_client",
    "NetPSError", "ProtocolError", "RPCTimeoutError", "ServerDrainingError",
    "LeaseExpiredError", "ServerClosedError", "EpochFencedError",
    "NotPrimaryError", "ShardPlanError",
    "SUPPORTED_DISCIPLINES", "commit_scale", "fold_delta",
]
