"""Shared endpoint-walk/failover mechanics for every netps-wire client.

Before this module, :class:`~distkeras_tpu.netps.client.PSClient` and the
serving plane's ``ServeClient`` each carried their own copy of the same
three ideas — split a comma-separated failover list, advance through it in
order on failure, and (for lease-granting servers) keep retrying until the
promotion window has genuinely elapsed. The sharded center plane adds a
third client that needs all three, so they live here once:

* **split** — :func:`distkeras_tpu.netps.wire.split_endpoints` order:
  primary first, then standbys in promotion-preference order;
* **walk order** — :meth:`EndpointWalker.walk` is a CAS advance (N stripe
  threads failing together move ONE step, not N); :meth:`EndpointWalker.
  advance` is the unconditional single-threaded-loop form ``ServeClient``
  uses. Both run the caller's teardown callback under the walker's lock so
  connection state can never straddle two endpoints;
* **patience window** — :meth:`EndpointWalker.patience`: with standbys
  configured the retry budget must bridge lease lapse + promotion (~2x
  the lease) plus one RPC deadline, however many attempts that takes;
  :func:`budget_left` is the loop guard that honors it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from distkeras_tpu.netps import wire
from distkeras_tpu.runtime import config


class EndpointWalker:
    """Ordered failover traversal of a ``"host:port[,host:port...]"``
    endpoint list. ``lock`` lets a caller share its own serialization
    domain (PSClient's fallback lock also guards the shm sweep, and the
    walk teardown must not interleave with it); by default the walker owns
    a private lock."""

    def __init__(self, endpoint: str,
                 lock: Optional[threading.Lock] = None):
        #: ordered (host, port) list — primary first, then standbys.
        self.endpoints = wire.split_endpoints(endpoint)
        self._idx = 0
        self._lock = lock if lock is not None else threading.Lock()

    def __len__(self) -> int:
        return len(self.endpoints)

    @property
    def index(self) -> int:
        """The current position (monotonic under :meth:`advance`; callers
        snapshot it as the ``seen_idx`` a later :meth:`walk` CASes on)."""
        return self._idx

    def current(self) -> tuple:
        return self.endpoints[self._idx % len(self.endpoints)]

    def walk(self, seen_idx: int,
             on_walk: Optional[Callable[[], None]] = None) -> bool:
        """CAS advance past a failure observed against ``seen_idx``: of N
        threads failing together exactly one wins and moves ONE step (the
        rest observe the already-moved index and do nothing). The winner's
        ``on_walk`` teardown runs under the lock — the next endpoint is a
        different process, so nothing negotiated with the old one may
        survive into a sibling's concurrent attempt. Single-endpoint
        walkers never walk (nothing is coming to save them). Returns
        whether THIS call advanced."""
        if len(self.endpoints) <= 1:
            return False
        with self._lock:
            walked = self._idx == seen_idx
            if walked:
                self._idx = (seen_idx + 1) % len(self.endpoints)
                if on_walk is not None:
                    on_walk()
        return walked

    def reorder(self, order: list,
                on_walk: Optional[Callable[[], None]] = None) -> None:
        """Adopt a new traversal order (health-aware clients float ready
        replicas to the front) and restart from its head. Must be a
        permutation — reordering may deprioritize an endpoint, never
        forget one. Teardown under the lock, same as :meth:`walk`."""
        if sorted(order) != sorted(self.endpoints):
            raise ValueError("reorder() needs a permutation of the "
                             "walker's endpoints")
        with self._lock:
            self.endpoints = list(order)
            self._idx = 0
            if on_walk is not None:
                on_walk()

    def advance(self, on_walk: Optional[Callable[[], None]] = None) -> None:
        """Unconditional advance — the single-threaded client form (one
        request in flight, every failure is ours). Teardown under the lock,
        same as :meth:`walk`."""
        with self._lock:
            self._idx += 1
            if on_walk is not None:
                on_walk()

    def patience(self, lease_s: Optional[float],
                 timeout: float) -> Optional[float]:
        """Monotonic deadline a multi-endpoint retry loop keeps walking
        until: 2x the lease (failure detection + standby promotion) plus
        one RPC deadline. ``None`` for a single endpoint — the strict
        attempt budget applies, failing fast is correct."""
        if len(self.endpoints) <= 1:
            return None
        lease = lease_s if lease_s else config.env_float("DKTPU_PS_LEASE")
        return time.monotonic() + 2.0 * float(lease or 0.0) + float(timeout)


def budget_left(attempt: int, attempts: int,
                patience: Optional[float]) -> bool:
    """May the retry loop go around again? The attempt budget, OR — when a
    patience window is set (multi-endpoint) — wall-clock inside it."""
    if attempt + 1 < attempts:
        return True
    return patience is not None and time.monotonic() < patience
