"""Join-time micro A/B probes: timed probe ops per candidate codec.

A probe is one round trip of the *actual* commit payload (the joined
center's tensor shapes) encoded under a candidate codec, answered by the
server's ``probe`` op — which decodes it exactly like a commit (so a
quantized candidate pays the real dequantize cost) but never touches the
fold, the journal, or the dedup table. The score is **logical f32 bytes
per second of round trip**: a codec that shrinks the wire 4x wins on a
slow link even after paying its quantize passes, and loses on the shm
ring where payload copies run at memcpy speed — the measured crossover
the bench A/B pinned, re-measured per connection at join time.

Old peers are unaffected by construction: the client only probes a peer
whose join reply carried the ``tuner`` caps bit; anything else returns
an empty result list and the static knobs stand.
"""

from __future__ import annotations

import time
from typing import NamedTuple, Optional, Sequence

import numpy as np

from distkeras_tpu.netps import wire
from distkeras_tpu.netps.errors import NetPSError
from distkeras_tpu.runtime import config


class ProbeResult(NamedTuple):
    """One candidate's timed micro A/B outcome. ``score`` is logical f32
    payload bytes moved per second of round-trip wall time — directly
    comparable across codecs because every candidate carries the SAME
    logical payload."""

    codec: str
    probes: int
    seconds: float
    payload_bytes: int
    score: float


def probe_codecs(client, template: Sequence[np.ndarray],
                 candidates: Optional[Sequence[str]] = None,
                 probes: Optional[int] = None) -> list:
    """Run the join-time micro A/B against ``client``'s joined peer.

    Returns one :class:`ProbeResult` per candidate codec, or ``[]`` when
    the peer does not advertise the ``tuner`` caps bit (old peer — left
    alone) or a probe fails mid-sweep (partial evidence is worse than
    none; the static knobs stand)."""
    from distkeras_tpu import telemetry

    caps = client.peer_caps or {}
    if not caps.get("tuner"):
        return []
    if probes is None:
        probes = config.env_int("DKTPU_TUNE_PROBES")
    probes = max(1, int(probes))
    if candidates is None:
        advertised = caps.get("codecs", ())
        candidates = [c for c in wire.CODECS
                      if c == wire.CODEC_NONE or c in advertised]
    payload = [np.ascontiguousarray(a, np.float32) for a in template]
    payload_bytes = sum(a.nbytes for a in payload)
    results: list = []
    for codec in candidates:
        t0 = time.monotonic()
        try:
            for _ in range(probes):
                hdr = client.probe(payload, codec=codec)
                if hdr is None:
                    return results
        except (NetPSError, OSError):
            # A probe is an optimisation, never a liability: a fault
            # mid-sweep (chaos, flaky link) abandons the sweep and the
            # static knobs stand — it must not kill the training run.
            return results
        dt = max(time.monotonic() - t0, 1e-9)
        res = ProbeResult(
            codec=codec, probes=probes, seconds=round(dt, 6),
            payload_bytes=payload_bytes * probes,
            score=round(payload_bytes * probes / dt, 1))
        results.append(res)
        telemetry.counter("tuner.probes").add(probes)
        telemetry.event("tuner_probe", {
            "codec": codec, "probes": probes, "seconds": res.seconds,
            "score": res.score})
    return results


def best_codec(results: Sequence[ProbeResult]) -> Optional[str]:
    """The winning candidate, or None with no evidence (empty sweep)."""
    if not results:
        return None
    return max(results, key=lambda r: r.score).codec
