"""Marginal-throughput elastic expansion: grow a job only while growing
pays.

The fleet scheduler's static quota/priority logic answers "may this job
have another worker"; this policy answers "did the LAST worker it got
actually move the needle". It watches each job's cumulative commit count
per scheduler tick, keeps a small table of measured commit rates per
granted-worker count, and blocks the next expansion when the current
rate is not at least ``(1 + DKTPU_TUNE_MIN_GAIN)`` times the best rate
measured at a smaller worker count — i.e. when marginal throughput has
flattened, the free slot is left for a tenant that can still use it.

Shrink paths (preemption, floors, gang minimums) are untouched: the
policy only gates *expansion*, so it can never cause a floor violation.
"""

from __future__ import annotations

import time
from typing import Optional

from distkeras_tpu.runtime import config


class MarginalThroughputPolicy:
    """Expansion gate fed by :meth:`observe` from the scheduler's gauge
    export (single scheduler thread; no locking needed). ``min_gain`` is
    the fractional rate improvement a grown worker count must show over
    the best smaller count to keep growing (``DKTPU_TUNE_MIN_GAIN``)."""

    #: seconds of observation at a worker count before its rate is
    #: trusted (shorter windows measure ramp-up noise, not throughput).
    MIN_WINDOW_S = 0.25

    def __init__(self, min_gain: Optional[float] = None):
        if min_gain is None:
            min_gain = config.env_float("DKTPU_TUNE_MIN_GAIN")
        self.min_gain = float(min_gain)
        #: label -> {"workers", "t0", "p0", "rates": {count: rate}}
        self._jobs: dict = {}

    def observe(self, label: str, workers: int, progress: int,
                now: Optional[float] = None) -> None:
        """Feed one scheduler-tick sample: the job's currently granted
        worker count and cumulative commit progress."""
        from distkeras_tpu import telemetry

        if now is None:
            now = time.monotonic()
        st = self._jobs.get(label)
        if st is None:
            self._jobs[label] = {"workers": int(workers), "t0": now,
                                 "p0": int(progress), "rates": {}}
            return
        dt = now - st["t0"]
        if int(workers) != st["workers"]:
            # Count changed: seal the finished window's rate, re-anchor.
            if dt >= self.MIN_WINDOW_S:
                st["rates"][st["workers"]] = (int(progress) - st["p0"]) / dt
            st.update(workers=int(workers), t0=now, p0=int(progress))
            return
        if dt >= self.MIN_WINDOW_S:
            # Same count: keep the current window's rate fresh.
            rate = (int(progress) - st["p0"]) / dt
            st["rates"][st["workers"]] = rate
            telemetry.gauge(f"tuner.marginal_tput.{label}").set(rate)

    def allow_expand(self, label: str, workers: int) -> bool:
        """May ``label`` grow beyond its current ``workers`` count?
        True without evidence (never starves a cold job); False when the
        measured rate at the current count failed to clear the marginal
        gain bar over the best smaller count."""
        from distkeras_tpu import telemetry

        st = self._jobs.get(label)
        if st is None:
            return True
        rates = st["rates"]
        cur = rates.get(int(workers))
        smaller = [r for n, r in rates.items() if n < int(workers)]
        if cur is None or not smaller:
            return True
        if cur >= max(smaller) * (1.0 + self.min_gain):
            return True
        telemetry.counter("tuner.expand_blocked").add(1)
        telemetry.event("tuner_expand_blocked", {
            "job": label, "workers": int(workers),
            "rate": round(cur, 3),
            "best_smaller": round(max(smaller), 3)})
        return False
