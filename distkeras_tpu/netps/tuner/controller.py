"""The online controller: gauges in, knob retunes out, guardrails always.

One :class:`Tuner` is shared by a run's worker threads. Worker 0 drives
the control loop (:meth:`Tuner.startup` at join, :meth:`Tuner.
maybe_decide` at round boundaries); every worker applies the current
target dialect through :meth:`Tuner.apply_to`, which routes the change
through the existing renegotiation paths (:meth:`~distkeras_tpu.netps.
client.PSClient.retune` + ``adopt_dialect``) — never a new wire surface,
so every exactly-once/fencing guarantee holds unchanged under a mid-run
retune.

Hysteresis and guardrails, in order of authority:

* **Floors are never violated.** Every target is clamped to its floor
  (inflight/shards >= 1, codec within the peer's advertised set) before
  it is published; a proposal that WOULD have crossed a floor counts in
  ``tuner.floor_violations`` (asserted zero by the chaos smoke) and is
  dropped.
* **Bounded retune rate.** One evaluation per ``DKTPU_TUNE_INTERVAL``
  rounds, one retune per knob per ``DKTPU_TUNE_COOLDOWN`` rounds, and at
  most ``DKTPU_TUNE_MAX_RETUNES`` mid-run retunes total — after which
  the controller holds whatever it converged to.
* **Oscillation falls back to static.** A knob that flips back to its
  previous value ``DKTPU_TUNE_OSC_LIMIT`` times in a row is frozen at
  its initial (static) value for the rest of the run
  (``tuner.oscillation_fallbacks`` + a ``tuner_fallback`` event).
* **Failover defers, never loses.** :meth:`apply_to` refuses to touch a
  client whose endpoint walker moved since the last check — the rejoin
  renegotiates the dialect anyway — and the undelivered generation is
  retried at the next round (``tuner.deferred``).
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple, Optional, Sequence

from distkeras_tpu.netps import wire
from distkeras_tpu.runtime import config

#: codec -> numeric gauge value (``tuner.knob.codec``): report-friendly
#: ordering by wire size (none > bf16 > int8).
_CODEC_GAUGE = {wire.CODEC_NONE: 0.0, wire.CODEC_BF16: 1.0,
                wire.CODEC_INT8: 2.0}


def autotune_enabled() -> bool:
    """The master switch (``DKTPU_NET_AUTOTUNE``), off by default."""
    return config.env_bool("DKTPU_NET_AUTOTUNE")


def recommended_topology(num_workers: int,
                         crossover: Optional[int] = None) -> str:
    """``"hier"`` at/above the measured fan-in crossover
    (``DKTPU_TUNE_HIER_FANIN``), ``"flat"`` below it — the bench
    ``hier_curve``'s break-even, as a one-liner the controller and the
    bench both consult."""
    if crossover is None:
        crossover = config.env_int("DKTPU_TUNE_HIER_FANIN")
    return "hier" if int(num_workers) >= int(crossover) else "flat"


class TunerConfig(NamedTuple):
    """The controller's knobs-about-knobs (the tuner env vars in the
    network table of docs/OBSERVABILITY.md — see :meth:`from_env`)."""

    interval: int
    cooldown: int
    probes: int
    max_retunes: int
    osc_limit: int
    hier_fanin: int
    min_gain: float
    hidden_floor: float
    stale_ceiling: float
    max_inflight: int = 4
    max_shards: int = 2

    @classmethod
    def from_env(cls) -> "TunerConfig":
        return cls(
            interval=max(1, config.env_int("DKTPU_TUNE_INTERVAL")),
            cooldown=max(1, config.env_int("DKTPU_TUNE_COOLDOWN")),
            probes=max(1, config.env_int("DKTPU_TUNE_PROBES")),
            max_retunes=max(0, config.env_int("DKTPU_TUNE_MAX_RETUNES")),
            osc_limit=max(1, config.env_int("DKTPU_TUNE_OSC_LIMIT")),
            hier_fanin=max(1, config.env_int("DKTPU_TUNE_HIER_FANIN")),
            min_gain=float(config.env_float("DKTPU_TUNE_MIN_GAIN")),
            hidden_floor=float(config.env_float("DKTPU_TUNE_HIDDEN_FLOOR")),
            stale_ceiling=float(config.env_float("DKTPU_TUNE_STALE_CEIL")),
        )


class Decision(NamedTuple):
    """One retune the controller took: which knob, from what to what, the
    gauge (or rule) that triggered it, and the round it landed in."""

    knob: str
    old: object
    new: object
    trigger: str
    round: int


class TunerState:
    """Per-worker apply-side cursor: the last target generation this
    worker's client adopted, and the endpoint-walk count seen at that
    adoption (the failover-deferral witness)."""

    __slots__ = ("generation", "walks")

    def __init__(self):
        self.generation = 0
        self.walks = 0


class Tuner:
    """One run's adaptive controller (see module docstring). ``inflight``
    is read directly by the worker loop every round (plain int read —
    safe under the GIL); codec/shards targets travel through the
    generation counter + :meth:`apply_to`."""

    def __init__(self, num_workers: int, inflight: int = 1,
                 cfg: Optional[TunerConfig] = None):
        self.cfg = cfg if cfg is not None else TunerConfig.from_env()
        self.num_workers = int(num_workers)
        self._lock = threading.Lock()
        #: bumped on every published target change; workers adopt via
        #: :meth:`apply_to` when their seen generation lags.
        self.generation = 0
        #: the overlap window target, clamped to [1, cfg.max_inflight].
        self.inflight = max(1, min(int(inflight), self.cfg.max_inflight))
        #: codec / striping targets; None = leave whatever the join
        #: negotiated (nothing published yet).
        self.codec: Optional[str] = None
        self.shards: Optional[int] = None
        #: the static values the run started with — the oscillation
        #: fallback restores these.
        self._initial: dict = {"inflight": self.inflight}
        #: first control-loop eval lands at r == interval, not r == 0: the
        #: gauges need a measured window before they are evidence (round
        #: 0's "overlap" is one blocking pull — always unhidden, always
        #: junk); the cold start is the probes' job, not the loop's.
        self._last_eval = 0
        #: connections the applying clients actually hold (set at
        #: startup); a shards-up proposal beyond it would be clamped at
        #: apply time into a phantom decision, so the loop never makes it.
        self.stripe_ceiling = 1
        self._last_retune: dict = {}
        self._prev_value: dict = {}
        self._flips: dict = {}
        self._frozen: set = set()
        self._agg = None
        self.decisions: list = []
        self.retunes = 0
        self.fallbacks = 0
        self.deferred = 0
        self.peer_codecs: tuple = wire.CODECS

    # -- startup: topology + join-time probes ---------------------------
    def choose_topology(self) -> str:
        """The start-of-run HIER decision, by the measured fan-in
        crossover (recorded as a decision like any retune)."""
        topo = recommended_topology(self.num_workers, self.cfg.hier_fanin)
        self._record(Decision("topology", None, topo,
                              "fan_in_crossover", -1), publish=False)
        return topo

    def attach_aggregator(self, agg) -> None:
        """Hand the controller the run's AggregatorServer so the control
        loop can retune its flush fan-in mid-run."""
        with self._lock:
            self._agg = agg

    def startup(self, client, template: Sequence) -> None:
        """The join-time micro A/B (worker 0, once): probe the candidate
        codecs over the actual negotiated connection and publish the
        winner — except on the shm ring, where the measured rule is
        unconditional (f32 over one ring wins; the codec is a TCP
        lever)."""
        from distkeras_tpu.netps.tuner.probe import best_codec, probe_codecs

        with self._lock:
            self._initial.setdefault("codec", client.codec)
            self._initial.setdefault("shards", client.active_shards)
            self.peer_codecs = tuple(
                (client.peer_caps or {}).get("codecs", ()))
            self.stripe_ceiling = len(getattr(client, "_conns", ()) or (1,))
        if client.active_transport in ("shm", "mesh"):
            # The PR 6 rule, applied rather than re-measured: quantize
            # passes cost more than the bytes they save at memcpy speed,
            # and a ring per stripe pays a doorbell per stripe. The mesh
            # dispatch is the limit case — zero wire bytes — so the same
            # rule applies a fortiori (its own trigger name, so the
            # decision log tells the dialects apart).
            rule = ("mesh_rule" if client.active_transport == "mesh"
                    else "shm_ring_rule")
            self.propose("codec", client.codec, wire.CODEC_NONE, rule, 0)
            self.propose("shards", client.active_shards, 1, rule, 0)
            return
        results = probe_codecs(client, template, probes=self.cfg.probes)
        winner = best_codec(results)
        if winner is not None and winner != client.codec:
            self.propose("codec", client.codec, winner, "probe", 0)

    # -- the control loop (worker 0, round boundaries) -------------------
    def maybe_decide(self, r: int, active_transport: str = "tcp") -> bool:
        """One control-loop evaluation, rate-limited to every
        ``cfg.interval`` rounds. Reads the live gauges and proposes at
        most one retune per knob; returns whether anything was
        published."""
        from distkeras_tpu import telemetry

        with self._lock:
            if r - self._last_eval < self.cfg.interval:
                return False
            self._last_eval = r
        tele = telemetry.get()

        def gauge(name):
            g = tele.gauge(name)
            return g.value if g.snapshot().get("count") else None

        hidden = gauge("netps.overlap.hidden_fraction")
        stale = gauge("discipline.staleness_mean")
        before = self.retunes + self.fallbacks
        # Overlap window: comms the compute loop still SEES means the
        # window is too small — widen it while staleness stays healthy;
        # staleness past the ceiling means the window outran the center —
        # narrow it (DynSGD-style pressure relief, but on the knob).
        if (hidden is not None and hidden < self.cfg.hidden_floor
                and (stale is None or stale <= self.cfg.stale_ceiling)
                and self.inflight < self.cfg.max_inflight):
            self.propose("inflight", self.inflight, self.inflight + 1,
                         "netps.overlap.hidden_fraction", r)
        elif (stale is not None and stale > self.cfg.stale_ceiling
                and self.inflight > 1):
            self.propose("inflight", self.inflight, self.inflight - 1,
                         "discipline.staleness_mean", r)
        # Codec: on the ring the rule is unconditional; on TCP, unhidden
        # comms with an f32 wire means bytes are the bottleneck — shrink
        # them (the probe usually already decided this at join).
        cur_codec = self.codec
        rule = "mesh_rule" if active_transport == "mesh" else "shm_ring_rule"
        if active_transport in ("shm", "mesh"):
            if cur_codec not in (None, wire.CODEC_NONE):
                self.propose("codec", cur_codec, wire.CODEC_NONE, rule, r)
        elif (cur_codec == wire.CODEC_NONE and hidden is not None
                and hidden < self.cfg.hidden_floor
                and wire.CODEC_INT8 in self.peer_codecs):
            self.propose("codec", cur_codec, wire.CODEC_INT8,
                         "netps.overlap.hidden_fraction", r)
        # Striping: concurrent stripe RPCs only help where the wire is
        # the serial resource (TCP); on the ring one stripe wins.
        cur_shards = self.shards
        if active_transport in ("shm", "mesh"):
            if cur_shards is not None and cur_shards > 1:
                self.propose("shards", cur_shards, 1, rule, r)
        elif (cur_shards in (None, 1) and hidden is not None
                and hidden < self.cfg.hidden_floor
                and min(self.cfg.max_shards, self.stripe_ceiling) > 1):
            self.propose("shards", cur_shards or 1,
                         min(2, self.cfg.max_shards, self.stripe_ceiling),
                         "netps.overlap.hidden_fraction", r)
        # Hierarchical combining: below the crossover the aggregator's
        # accumulation window buys nothing — flush per commit (a
        # pass-through forwarder); at/above it, combine the full fan-in.
        agg = self._agg
        if agg is not None:
            fan = gauge("netps.hier.fan_in")
            if fan is not None:
                want = None if fan >= self.cfg.hier_fanin else 1
                if agg.fan_in != want:
                    self.propose("hier_fan_in", agg.fan_in, want,
                                 "netps.hier.fan_in", r, apply=lambda:
                                 agg.set_fan_in(want))
        return (self.retunes + self.fallbacks) > before

    # -- proposals: hysteresis, floors, oscillation ----------------------
    def propose(self, knob: str, old, new, trigger: str, r: int,
                apply=None) -> bool:
        """One retune proposal through every guardrail; publishes (bumps
        the generation) and returns True only if it survives. ``apply``
        is an optional side-effecting closure for knobs that do not
        travel through the client dialect (the aggregator fan-in)."""
        from distkeras_tpu import telemetry

        with self._lock:
            if new == old or knob in self._frozen:
                return False
            if knob != "topology" and self.retunes >= self.cfg.max_retunes:
                return False
            last = self._last_retune.get(knob)
            if last is not None and r - last < self.cfg.cooldown:
                return False
            if not self._floor_ok_locked(knob, new):
                self.retunes += 1  # a dropped proposal still spends budget
                telemetry.counter("tuner.floor_violations").add(1)
                return False
            # Oscillation: flipping back to the previous value counts a
            # flip; enough consecutive flips freezes the knob at its
            # static initial value for the rest of the run.
            if self._prev_value.get(knob) == new:
                self._flips[knob] = self._flips.get(knob, 0) + 1
            else:
                self._flips[knob] = 0
            if self._flips[knob] >= self.cfg.osc_limit:
                self._frozen.add(knob)
                self.fallbacks += 1
                fallback = self._initial.get(knob, old)
                self._publish_locked(knob, fallback)
                telemetry.counter("tuner.oscillation_fallbacks").add(1)
                telemetry.event("tuner_fallback", {
                    "knob": knob, "restored": fallback, "round": r,
                    "reason": f"oscillated {self._flips[knob]}x"})
                return True
            self._prev_value[knob] = old
            self._last_retune[knob] = r
            self.retunes += 1
            self._publish_locked(knob, new)
        if apply is not None:
            apply()
        self._record(Decision(knob, old, new, trigger, r), publish=False)
        return True

    def _floor_ok_locked(self, knob: str, new) -> bool:
        if knob == "inflight":
            return 1 <= int(new) <= self.cfg.max_inflight
        if knob == "shards":
            return 1 <= int(new) <= self.cfg.max_shards
        if knob == "codec":
            return new == wire.CODEC_NONE or new in self.peer_codecs
        return True

    def _publish_locked(self, knob: str, value) -> None:
        if knob == "inflight":
            self.inflight = int(value)
        elif knob == "codec":
            self.codec = value
        elif knob == "shards":
            self.shards = int(value)
        if knob in ("codec", "shards"):
            self.generation += 1

    def _record(self, d: Decision, publish: bool) -> None:
        from distkeras_tpu import telemetry

        with self._lock:
            self.decisions.append(d)
            if publish:
                self._publish_locked(d.knob, d.new)
        telemetry.counter("tuner.decisions").add(1)
        telemetry.counter(f"tuner.decision.{d.knob}").add(1)
        telemetry.event("tuner_decision", {
            "knob": d.knob, "from": d.old, "to": d.new,
            "trigger": d.trigger, "round": d.round})
        gauge_val = (_CODEC_GAUGE.get(d.new) if d.knob == "codec"
                     else d.new if isinstance(d.new, (int, float))
                     else None)
        if gauge_val is not None:
            telemetry.gauge(f"tuner.knob.{d.knob}").set(float(gauge_val))

    # -- the apply side (every worker) -----------------------------------
    def apply_to(self, client, template: Sequence,
                 state: TunerState) -> Optional[dict]:
        """Adopt the current target dialect onto one worker's client.
        Returns the change dict from :meth:`PSClient.retune` when a new
        generation was applied, None when there was nothing to do — or
        when the adoption was DEFERRED because a failover walk moved the
        client's endpoint since the last check (the rejoin renegotiates
        the dialect; the unseen generation is retried next round, never
        lost). The caller must have quiesced its in-flight commits first
        (remote.py drains its ordered lane before calling)."""
        from distkeras_tpu import telemetry

        with self._lock:
            gen, codec, shards = self.generation, self.codec, self.shards
        if gen == state.generation:
            return None
        walks = getattr(client, "walk_count", 0)
        if walks != state.walks:
            state.walks = walks
            with self._lock:
                self.deferred += 1
            telemetry.counter("tuner.deferred").add(1)
            return None
        from distkeras_tpu.telemetry import tracing

        with tracing.trace_scope("tuner.retune", generation=gen,
                                 codec=codec, shards=shards):
            changed = client.retune(codec=codec, shards=shards,
                                    template=template)
        state.generation = gen
        return changed

    # -- end-of-run summary ----------------------------------------------
    def export_summary(self, client=None) -> dict:
        """The converged dialect + decision counts, as gauges and one
        ``tuner_run_summary`` event (what the bench's auto arm reads)."""
        from distkeras_tpu import telemetry

        with self._lock:
            summary = {
                "inflight": self.inflight,
                "codec": self.codec,
                "shards": self.shards,
                "decisions": len(self.decisions),
                "retunes": self.retunes,
                "fallbacks": self.fallbacks,
                "deferred": self.deferred,
            }
        if client is not None:
            summary["codec"] = client.codec
            summary["shards"] = client.active_shards
            summary["transport"] = client.active_transport
        telemetry.gauge("tuner.knob.inflight").set(float(summary["inflight"]))
        if summary["codec"] is not None:
            telemetry.gauge("tuner.knob.codec").set(
                float(_CODEC_GAUGE.get(summary["codec"], -1.0)))
        if summary["shards"] is not None:
            telemetry.gauge("tuner.knob.shards").set(float(summary["shards"]))
        telemetry.event("tuner_run_summary", dict(summary))
        return summary


# Deterministic-time hook for tests (time.monotonic by default).
_now = time.monotonic
