"""Self-tuning data plane: the controller that closes the loop from
telemetry to knobs.

The data plane's knob space (``DKTPU_NET_INFLIGHT`` / ``COMPRESS`` /
``SHARDS`` / ``TRANSPORT`` / ``HIER``) is context-dependent by our own
bench evidence: int8 wins on cross-host TCP but loses on the shm ring
(quantize cost exceeds bytes saved at memcpy speed), and hierarchical
aggregation only beats flat topology above a ~4-worker fan-in. Nobody
hand-tunes env vars per job at fleet scale, so — gated by
``DKTPU_NET_AUTOTUNE=1``, off by default — this package:

* runs **join-time micro A/B probes** (:mod:`~distkeras_tpu.netps.tuner.
  probe`): a few timed probe ops per candidate codec, piggybacked on the
  existing capability negotiation (a peer without the ``tuner`` caps bit
  simply answers the typed unknown-op error and is left alone — old peers
  are unaffected);
* runs an **online control loop** (:class:`~distkeras_tpu.netps.tuner.
  controller.Tuner`) over the gauges the run already exports
  (``netps.overlap.hidden_fraction``, ``discipline.staleness_mean``,
  ``netps.fold.tensors_per_sec``, ``netps.hier.fan_in``) and retunes
  compression / inflight / striping mid-run through the existing
  renegotiation paths (:meth:`PSClient.retune` + ``adopt_dialect``; caps
  re-adoption on rejoin), flips the hierarchical topology per the
  measured fan-in crossover, and — with hysteresis, per-knob cooldowns,
  and an oscillation fallback to the static knobs — never violates a
  floor and keeps every exactly-once/fencing guarantee intact;
* gates **fleet elastic expansion on measured marginal throughput**
  (:class:`~distkeras_tpu.netps.tuner.fleet.MarginalThroughputPolicy`)
  instead of static quotas alone: an expansion whose last granted worker
  did not move the job's commit rate is not repeated.

Every decision is a telemetry event (``tuner_decision`` /
``tuner_probe`` / ``tuner_fallback``) plus counters, rendered by
``python -m distkeras_tpu.telemetry report`` as the Tuner section.
"""

from distkeras_tpu.netps.tuner.controller import (
    Decision,
    Tuner,
    TunerConfig,
    TunerState,
    autotune_enabled,
    recommended_topology,
)
from distkeras_tpu.netps.tuner.fleet import MarginalThroughputPolicy
from distkeras_tpu.netps.tuner.probe import ProbeResult, best_codec, probe_codecs

__all__ = [
    "Decision",
    "MarginalThroughputPolicy",
    "ProbeResult",
    "Tuner",
    "TunerConfig",
    "TunerState",
    "autotune_enabled",
    "best_codec",
    "probe_codecs",
    "recommended_topology",
]
