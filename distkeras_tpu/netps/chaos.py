"""Network-fault chaos: an in-process, frame-aware TCP proxy.

Sits between :class:`~distkeras_tpu.netps.client.PSClient` and
:class:`~distkeras_tpu.netps.server.PSServer` and injects the failure
modes that dominate production PS training — slow links, lost packets,
duplicated delivery, mid-frame connection death, and partitions — without
needing a real bad network. Because the wire protocol is length-prefixed,
the proxy operates on whole *frames*: it reads one client request at a
time, consults the fault plan by the frame's global index, and forwards
(or delays, drops, duplicates, truncates...) deterministically.

Faults come from the PR 2 grammar, extended
(``resilience.FaultPlan.parse_net`` / ``DKTPU_NET_FAULTS``), one-shot each::

    DKTPU_NET_FAULTS="delay@3:0.2;drop@5;dup@6;truncate@8;partition@7:2"

=================  =====================================================
``delay@F:S``      hold request frame F for S seconds before forwarding
``drop@F``         swallow request frame F (no forward, no reply — the
                   client times out and retries)
``dup@F``          forward request frame F twice (the server sees a
                   retransmit; commit dedup answers the copy)
``truncate@F``     forward only half of frame F, then kill that upstream
                   connection (death mid-frame; crc/framing rejects it)
``partition@F:S``  at frame F sever every connection and refuse new ones
                   for S seconds (both directions dark)
``delay_r/drop_r/dup_r/truncate_r@F``  the same, applied to the *reply*
                   of request frame F — ``drop_r`` is the lost-ACK case
                   the idempotent commit seq exists for
``evict@R:S``      consumed by the remote worker loop, not the proxy: the
                   seeded worker goes silent S seconds at round R so its
                   lease expires (eviction + rejoin mid-run)
=================  =====================================================

Frame indices count client->server requests through this proxy, 0-based,
across all connections — deterministic for a single-worker flow; for many
racing workers the index selects "some" frame, which is exactly what chaos
needs.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from distkeras_tpu.netps import wire
from distkeras_tpu.netps.errors import ProtocolError
from distkeras_tpu.resilience import faults as _faults

_POLL_S = 0.2
_UPSTREAM_REPLY_S = 30.0


class ChaosProxy:
    """Frame-aware MITM between netps clients and one upstream server.

    ``plan`` defaults to the ambient network plan (``DKTPU_NET_FAULTS``);
    ``None``/empty forwards everything untouched (a latency-only proxy).
    Point clients at :attr:`endpoint` instead of the server's.
    """

    def __init__(self, upstream: str, plan: Optional[_faults.FaultPlan] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.upstream = upstream
        self.plan = plan if plan is not None else _faults.active_net_plan()
        self._lock = threading.Lock()
        self._frames = 0
        self._partition_until = 0.0
        self._conns: list = []
        self._stop = threading.Event()
        self._listener = socket.create_server((host, port))
        self._listener.settimeout(_POLL_S)
        self._host = host
        self._port = self._listener.getsockname()[1]
        self._threads: list = []
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"{self._host}:{self._port}"

    @property
    def frames_seen(self) -> int:
        return self._frames

    def start(self) -> "ChaosProxy":
        t = threading.Thread(target=self._accept_loop, name="chaos-accept")
        t.start()
        self._accept_thread = t
        return self

    def close(self) -> None:
        self._stop.set()
        if self._accept_thread is not None:
            self._accept_thread.join()
        self._sever_all()
        for t in list(self._threads):
            t.join()
        try:
            self._listener.close()
        except OSError:
            pass

    # ------------------------------------------------------------------
    def _fire(self, kind: str, at: int) -> Optional[float]:
        if self.plan is None:
            return None
        return self.plan.fire(kind, at)

    def _partitioned(self) -> bool:
        return time.monotonic() < self._partition_until

    def _sever_all(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _track(self, *socks) -> None:
        with self._lock:
            self._conns.extend(socks)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if self._partitioned():
                # The network is dark: a connection reset, not a listen
                # backlog — the client sees it instantly and backs off.
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="chaos-handler")
            t.start()
            self._threads.append(t)

    # ------------------------------------------------------------------
    def _handle(self, client: socket.socket) -> None:
        from distkeras_tpu import telemetry

        try:
            upstream = socket.create_connection(
                wire.split_endpoint(self.upstream), timeout=_UPSTREAM_REPLY_S)
        except OSError:
            try:
                client.close()
            except OSError:
                pass
            return
        upstream.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        client.settimeout(_POLL_S)
        self._track(client, upstream)
        with client, upstream:
            while not self._stop.is_set() and not self._partitioned():
                try:
                    prefix = wire.recv_exact(client, wire.PREFIX_SIZE)
                    client.settimeout(_UPSTREAM_REPLY_S)
                    raw = wire.finish_raw_frame(client, prefix)
                    client.settimeout(_POLL_S)
                except socket.timeout:
                    continue
                except (ConnectionError, OSError, ProtocolError):
                    return
                with self._lock:
                    i = self._frames
                    self._frames += 1
                try:
                    if not self._inject(i, raw, client, upstream, telemetry):
                        return
                except (ConnectionError, OSError, ProtocolError):
                    return

    def _inject(self, i: int, raw: bytes, client: socket.socket,
                upstream: socket.socket, telemetry) -> bool:
        """Apply frame ``i``'s faults; False = tear this path down."""
        arg = self._fire("partition", i)
        if arg is not None:
            self._partition_until = time.monotonic() + (arg or 1.0)
            telemetry.event("chaos_partition", {"frame": i, "seconds": arg})
            self._sever_all()
            return False
        if self._fire("drop", i) is not None:
            telemetry.event("chaos_drop", {"frame": i})
            return True  # swallowed: no forward, no reply
        arg = self._fire("delay", i)
        if arg is not None:
            telemetry.event("chaos_delay", {"frame": i, "seconds": arg})
            time.sleep(arg)
        if self._fire("truncate", i) is not None:
            telemetry.event("chaos_truncate", {"frame": i})
            upstream.sendall(raw[:max(1, len(raw) // 2)])
            return False  # died mid-frame: connection is unrecoverable
        copies = 2 if self._fire("dup", i) is not None else 1
        if copies == 2:
            telemetry.event("chaos_dup", {"frame": i})
        for _ in range(copies):
            upstream.sendall(raw)
        for _ in range(copies):
            if not self._relay_reply(i, client, upstream, telemetry):
                return False
        return True

    def _relay_reply(self, i: int, client: socket.socket,
                     upstream: socket.socket, telemetry) -> bool:
        reply = wire.read_raw_frame(upstream)
        if self._fire("drop_r", i) is not None:
            # The lost ACK: the server already applied the request; the
            # client times out and retransmits — dedup must make the
            # retransmit fold-exactly-once.
            telemetry.event("chaos_drop_reply", {"frame": i})
            return True
        arg = self._fire("delay_r", i)
        if arg is not None:
            telemetry.event("chaos_delay_reply", {"frame": i, "seconds": arg})
            time.sleep(arg)
        if self._fire("truncate_r", i) is not None:
            telemetry.event("chaos_truncate_reply", {"frame": i})
            client.sendall(reply[:max(1, len(reply) // 2)])
            return False
        copies = 2 if self._fire("dup_r", i) is not None else 1
        if copies == 2:
            telemetry.event("chaos_dup_reply", {"frame": i})
        for _ in range(copies):
            client.sendall(reply)
        return True
