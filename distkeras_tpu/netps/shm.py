"""The same-host fast path: a shared-memory ring under the wire protocol.

PR 5 made the TCP plane as fast as sockets allow (sendmsg scatter-gather,
``recv_into``, codecs, striping) and recovered ~14 % of the in-process gap
— the rest is the kernel: every commit still crosses the socket buffer
twice. When client and server share a host there is no reason to involve
the network stack at all, so this module moves the *payload* into an
mmap'd segment and keeps only a doorbell on a Unix-domain socket:

* **Negotiation** rides the existing caps handshake: a server willing to
  serve rings advertises ``caps["shm"] = {"boot_id", "uds"}`` in its join
  reply (``PSServer``), and a client configured with
  ``DKTPU_NET_TRANSPORT=shm`` upgrades its data connections iff the
  advertised boot id equals :func:`local_boot_id` — the same-host check.
  Everything else (old peer, cross-host, ``tcp`` mode) silently stays on
  the PR 5 TCP dialect; no guarantee depends on the upgrade.
* **Attach**: the *client* creates one segment per direction (unlinked
  tempfiles in ``/dev/shm``) and passes the fds over the UDS via
  ``SCM_RIGHTS`` — the server never trusts a path, and a dead peer's
  segments vanish with the last fd.
* **Transfer**: a frame is built straight into the slot (ONE copy per
  array buffer, crc computed incrementally over the same views — the shm
  analogue of ``wire.send_frame``), then an 8-byte doorbell carrying the
  frame length crosses the UDS. The reader copies the frame out of the
  slot into a fresh buffer (ONE copy — the analogue of ``recv_into``) and
  decodes views over it, so the frame-buffer ownership contract of
  ``wire.read_frame`` holds unchanged. Slot layout and the seqlock/crc
  rules live in ``wire.py`` next to the rest of the wire spec.
* **Failure = ProtocolError/ConnectionError/socket.timeout** — exactly
  the taxonomy the retry/lease/dedup machinery already speaks, raised
  from the doorbell socket or the slot checks. A torn or corrupt slot
  kills the connection; the client reconnects with FRESH segments and
  retransmits under the same seq; the server's dedup keeps it
  exactly-once. Nothing above this module knows the transport changed.

Chaos hooks (``DKTPU_NET_FAULTS``, consumed here because no TCP proxy can
sit on a memory ring): ``shm_delay@F:S`` holds ring frame F for S seconds
before ringing its doorbell; ``shm_corrupt@F`` flips the slot's crc after
the write, so the reader rejects the frame and the connection dies — the
ring's version of ``truncate``. F counts client->server ring frames
process-wide, like the proxy's frame index.
"""

from __future__ import annotations

import mmap
import os
import socket
import tempfile
import threading
import time
import zlib
from typing import Optional

from distkeras_tpu.netps import wire
from distkeras_tpu.netps.errors import ProtocolError
from distkeras_tpu.resilience import faults as _faults
from distkeras_tpu.runtime import config

#: initial per-direction slot capacity; grows (ftruncate + remap) to fit the
#: largest frame the connection has carried.
_INITIAL_BYTES = 1 << 16

TRANSPORTS = ("tcp", "shm", "mesh")


def transport_mode() -> str:
    """The configured transport dialect (``DKTPU_NET_TRANSPORT``), validated."""
    mode = config.env_str("DKTPU_NET_TRANSPORT")
    if mode not in TRANSPORTS:
        raise ValueError(
            f"DKTPU_NET_TRANSPORT={mode!r} is not a known transport; "
            f"known: {list(TRANSPORTS)}")
    return mode


def local_boot_id() -> str:
    """This host's boot id — two processes reading the same value share a
    kernel, hence a page cache, hence may speak shm. Falls back to the
    hostname off Linux (weaker, but those platforms also lack ``/dev/shm``
    semantics worth optimizing for)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:  # pragma: no cover - non-Linux
        return f"host:{socket.gethostname()}"


def endpoint_visible(uds_path: str) -> bool:
    """Whether the advertised doorbell socket is reachable from THIS
    process's filesystem namespace. A shared kernel (boot-id match) is
    necessary but not sufficient: two containers on one node share a boot
    id while the server's UDS path lives in its own mount namespace — the
    upgrade must fall back to TCP there instead of burning retry budget
    on a socket that can never connect."""
    try:
        return os.path.exists(uds_path)
    except OSError:  # pragma: no cover - exotic fs errors = not visible
        return False


# -- ring frame counter (chaos index) ---------------------------------------
_frames_lock = threading.Lock()
_frames = 0


def _next_frame() -> int:
    global _frames
    with _frames_lock:
        i = _frames
        _frames += 1
        return i


def reset_frames() -> None:
    """Zero the process-wide ring frame counter (tests pin fault indices)."""
    global _frames
    with _frames_lock:
        _frames = 0


# ---------------------------------------------------------------------------
# One direction: a seqlock'd slot over an mmap'd file
# ---------------------------------------------------------------------------

class Slot:
    """One direction's slot (layout in ``wire.py``). The creating side
    writes, the attached side reads; both remap as the file grows.

    Ops and :meth:`close` serialize on a per-slot lock: the client's
    shm->TCP fallback closes EVERY connection's ring, including ones a
    sibling stripe thread is mid-operation on — without the lock that
    teardown yanks the mmap out from under the op (``ValueError``, which
    the retry machinery does not speak) and ``os.close`` frees an fd
    number the op may still hand to ``ftruncate``. With it, close waits
    out the (short, CPU-bound) op and later ops raise the retryable
    ``ConnectionError`` taxonomy."""

    def __init__(self, fd: int, size: Optional[int] = None):
        self.fd = fd
        self._op_lock = threading.Lock()
        self._closed = False
        self._size = int(size if size is not None else os.fstat(fd).st_size)
        if self._size < wire.SHM_SLOT_HEADER:
            os.ftruncate(fd, _INITIAL_BYTES)
            self._size = _INITIAL_BYTES
        self._mm = mmap.mmap(fd, self._size)
        self._seq = wire.U32.unpack_from(self._mm, wire.SHM_SEQ_OFF)[0]

    def _remap(self, size: int) -> None:
        self._mm.close()
        self._size = size
        self._mm = mmap.mmap(self.fd, size)

    def _ensure(self, payload_bytes: int) -> None:
        """Writer-side growth: make room for a frame of ``payload_bytes``."""
        need = wire.SHM_SLOT_HEADER + payload_bytes
        if need > self._size:
            size = max(need, 2 * self._size)
            size += (-size) % mmap.PAGESIZE
            os.ftruncate(self.fd, size)
            self._remap(size)

    def _refresh(self, payload_bytes: int) -> None:
        """Reader-side growth: the doorbell announced a frame larger than
        our mapping — the writer grew the file; follow it."""
        need = wire.SHM_SLOT_HEADER + payload_bytes
        if need > self._size:
            size = os.fstat(self.fd).st_size
            if size < need:
                raise ProtocolError(
                    f"doorbell announces a {payload_bytes}-byte frame but "
                    f"the segment holds {size} bytes")
            self._remap(size)

    def write_frame(self, kind: int, header: dict, arrays=()) -> int:
        """Build one wire frame straight into the slot under the seqlock
        (single-copy: each array buffer lands in the segment exactly once).
        The slot crc covers the frame's header section only — the payload
        has no lossy channel to defend against here (see the layout notes
        in ``wire.py``). Returns the frame's byte count — what the
        doorbell announces."""
        buffers, total = wire._frame_buffers(kind, header, arrays,
                                             body_crc=False)
        with self._op_lock:
            return self._write_frame_locked(buffers, total)

    def _write_frame_locked(self, buffers, total: int) -> int:
        if self._closed:
            raise ConnectionError("ring slot closed during write")
        self._ensure(total)
        mm = self._mm
        self._seq = (self._seq + 1) & 0xFFFFFFFF  # odd: write in progress
        wire.U32.pack_into(mm, wire.SHM_SEQ_OFF, self._seq)
        off = wire.SHM_SLOT_HEADER
        crc = 0
        for i, b in enumerate(buffers):
            v = wire._byte_view(b)
            n = v.nbytes
            if n:
                mm[off:off + n] = v
                if i == 0:  # buffers[0] is the prefix + JSON header section
                    crc = zlib.crc32(v, crc)
                off += n
        wire._SHM_SLOT.pack_into(mm, 0, wire.SHM_MAGIC, wire.SHM_VERSION,
                                 self._seq, crc, total, 0)
        self._seq = (self._seq + 1) & 0xFFFFFFFF  # even: complete
        wire.U32.pack_into(mm, wire.SHM_SEQ_OFF, self._seq)
        return total

    def corrupt_crc(self) -> None:
        """Flip the slot's crc (the ``shm_corrupt`` chaos hook): the reader
        must reject the frame and tear the connection down."""
        with self._op_lock:
            if self._closed:
                raise ConnectionError("ring slot closed")
            (crc,) = wire.U32.unpack_from(self._mm, wire.SHM_CRC_OFF)
            wire.U32.pack_into(self._mm, wire.SHM_CRC_OFF, crc ^ 0xFFFFFFFF)

    def read_frame(self, length: int, decode: bool = True,
                   ) -> tuple[int, int, dict, list]:
        """Copy + verify + decode the announced frame out of the slot:
        ``(kind, nbytes, header, arrays)``. ONE copy — the decoded arrays
        are views over a fresh private buffer, never over the slot (the
        next frame overwrites it)."""
        with self._op_lock:
            return self._read_frame_locked(length, decode)

    def _read_frame_locked(self, length: int, decode: bool,
                           ) -> tuple[int, int, dict, list]:
        if self._closed:
            raise ConnectionError("ring slot closed during read")
        if length > wire.max_frame_bytes():
            raise ProtocolError(
                f"ring frame of {length} bytes exceeds DKTPU_NET_MAX_FRAME="
                f"{wire.max_frame_bytes()}")
        if length < wire.PREFIX_SIZE:
            raise ProtocolError(f"ring frame too short ({length} bytes)")
        self._refresh(length)
        mm = self._mm
        magic, version, seq1, crc, slot_len, _rsvd = \
            wire._SHM_SLOT.unpack_from(mm, 0)
        if magic != wire.SHM_MAGIC:
            raise ProtocolError(f"bad slot magic {magic:#x}")
        if version != wire.SHM_VERSION:
            raise ProtocolError(f"unsupported slot version {version}")
        if seq1 & 1:
            raise ProtocolError("torn slot read (write in progress)")
        if slot_len != length:
            raise ProtocolError(
                f"slot declares {slot_len} bytes, doorbell announced {length}")
        hdr_end = wire.SHM_SLOT_HEADER
        # THE single copy — memoryview slice assignment is a raw memcpy
        # (~12 GB/s); bytes(mm[a:b]) measures 6x slower on the same pages.
        frame = bytearray(length)
        memoryview(frame)[:] = memoryview(mm)[hdr_end:hdr_end + length]
        (seq2,) = wire.U32.unpack_from(mm, wire.SHM_SEQ_OFF)
        if seq2 != seq1:
            raise ProtocolError("torn slot read (writer raced the copy)")
        kind, _hdr_crc, body_len = wire.parse_prefix(
            frame[:wire.PREFIX_SIZE], max_frame=length)
        if wire.PREFIX_SIZE + body_len != length:
            raise ProtocolError(
                f"frame declares {body_len} body bytes inside a "
                f"{length}-byte slot frame")
        # Slot crc covers the header section: prefix + HLEN + JSON header
        # (the bytes that drive allocation/dispatch; payload integrity is
        # the seqlock + coherent memory — see wire.py layout notes).
        if length < wire.PREFIX_SIZE + 4:
            raise ProtocolError(f"ring frame too short ({length} bytes)")
        (hlen,) = wire.U32.unpack_from(frame, wire.PREFIX_SIZE)
        head_end = min(wire.PREFIX_SIZE + 4 + hlen, length)
        if zlib.crc32(memoryview(frame)[:head_end]) != crc:
            raise ProtocolError("slot checksum mismatch (corrupt ring frame)")
        header, arrays = wire._decode_body(
            memoryview(frame)[wire.PREFIX_SIZE:], decode=decode)
        return kind, length, header, arrays

    def close(self) -> None:
        with self._op_lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._mm.close()
            except (BufferError, ValueError):  # exported views still alive
                pass
            try:
                os.close(self.fd)
            except OSError:
                pass


def create_slot() -> Slot:
    """A fresh, already-unlinked segment (client side; the fd is the only
    handle and travels over the UDS via SCM_RIGHTS)."""
    dir_ = "/dev/shm" if os.path.isdir("/dev/shm") else None
    fd, path = tempfile.mkstemp(prefix="dknetps-ring-", dir=dir_)
    os.unlink(path)
    os.ftruncate(fd, _INITIAL_BYTES)
    slot = Slot(fd, _INITIAL_BYTES)
    wire._SHM_SLOT.pack_into(slot._mm, 0, wire.SHM_MAGIC, wire.SHM_VERSION,
                             0, 0, 0, 0)
    return slot


def accept_attach(conn: socket.socket) -> tuple[Slot, Slot]:
    """Server side of the attach: receive the (c2s, s2c) segment fds the
    connecting client passed over the UDS."""
    msg, fds, _flags, _addr = socket.recv_fds(conn, 64, 2)
    if not msg:
        raise ConnectionError("UDS closed before attach")
    if len(fds) != 2:
        for fd in fds:
            os.close(fd)
        raise ProtocolError(f"shm attach carried {len(fds)} fds, expected 2")
    # A Slot ctor that raises (fstat/ftruncate/mmap, e.g. ENOMEM) has NOT
    # taken ownership of its fd — close what it and the earlier slot held,
    # or every failed attach leaks 2 fds + a mapping until EMFILE.
    c2s = None
    try:
        c2s = Slot(fds[0])
        return c2s, Slot(fds[1])
    except BaseException:
        try:
            os.close(fds[1])
        except OSError:
            pass
        if c2s is not None:
            c2s.close()
        else:
            try:
                os.close(fds[0])
            except OSError:
                pass
        raise


# ---------------------------------------------------------------------------
# Client-side connection: two slots + the UDS doorbell
# ---------------------------------------------------------------------------

class ShmConnection:
    """One upgraded data connection: request slot, reply slot, doorbell.

    Mirrors the TCP connection's contract exactly — ``settimeout`` guards
    the doorbell waits, failures raise the retryable taxonomy, and strict
    request/reply alternation per connection is assumed (what ``PSClient``
    already guarantees per ``_Conn``)."""

    def __init__(self, uds_path: str, timeout: float):
        if timeout <= 0:
            raise socket.timeout("deadline exceeded before shm attach")
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            self.sock.settimeout(timeout)
            self.sock.connect(uds_path)
            self.c2s = create_slot()
            self.s2c = create_slot()
            socket.send_fds(self.sock, [b"DKATTACH"],
                            [self.c2s.fd, self.s2c.fd])
        except BaseException:
            self.close()
            raise

    def settimeout(self, t: float) -> None:
        self.sock.settimeout(t)

    def send(self, kind: int, header: dict, arrays=()) -> int:
        """Write the frame into the request slot and ring the doorbell;
        returns frame bytes (telemetry). The chaos hooks fire here."""
        nbytes = self.c2s.write_frame(kind, header, arrays)
        plan = _faults.active_net_plan()
        if plan is not None:
            i = _next_frame()
            arg = plan.fire("shm_delay", i)
            if arg:
                from distkeras_tpu import telemetry

                telemetry.event("chaos_shm_delay", {"frame": i, "seconds": arg})
                time.sleep(arg)
            if plan.fire("shm_corrupt", i) is not None:
                from distkeras_tpu import telemetry

                telemetry.event("chaos_shm_corrupt", {"frame": i})
                self.c2s.corrupt_crc()
        self.sock.sendall(wire.pack_doorbell(nbytes))
        return nbytes

    def recv(self, decode: bool = True) -> tuple[int, int, dict, list]:
        """Wait for the reply doorbell (under the socket timeout) and read
        the reply frame out of the reply slot."""
        raw = wire.recv_exact(self.sock, wire.SHM_DOORBELL_SIZE)
        return self.s2c.read_frame(wire.unpack_doorbell(raw), decode=decode)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
        for slot in (getattr(self, "c2s", None), getattr(self, "s2c", None)):
            if slot is not None:
                slot.close()
