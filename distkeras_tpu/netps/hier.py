"""Hierarchical two-level folds: a per-host aggregator in front of the root.

Flat topology: W workers -> root, W commits per round at the root's
ingress. With ``DKTPU_NET_HIER=1`` each host interposes an
:class:`AggregatorServer` — a real :class:`~distkeras_tpu.netps.server.
PSServer` facade its workers join exactly like a root (same wire, same
leases, same dedup, and the shm ring when negotiated: the local hop is
where the ring pays) — that **pre-combines** its workers' commits and
forwards ONE combined commit upstream per flush, cutting root ingress by
the worker fan-in.

Semantics, against the discipline rule:

* Worker-normalized deltas are **additive**: for every scale-1 discipline
  (downpour/adag/aeasgd/eamsgd) folding ``sum(d_i)`` equals folding each
  ``d_i`` in turn, so the flat and hierarchical topologies produce the
  SAME center (tested exactly in ``tests/test_netps_shm.py``).
* The combined commit's **pull-time counter is the min** of its
  constituents': the root's counter rule then charges the combined commit
  the staleness of its *oldest* constituent — the conservative reading of
  the existing discipline rule, which matters only for DynSGD's
  ``1/(staleness+1)`` scale (one scale for the combined commit, as for
  any single commit).
* The aggregator's local update counter **mirrors the root's lineage**:
  it only advances when a flush lands and the fresh root center is
  re-pulled, so worker ``pulled`` counters — and therefore local lease
  renewals, dedup, and the staleness the workers are charged — are all in
  root units. Workers' retransmits dedup locally; the aggregator's own
  commits dedup at the root: exactly-once holds at both levels.
* A flush whose upstream commit is **evicted** (the aggregator's lease
  lapsed) loses that combined window — the same semantics as a flat
  worker's evicted commit — and the aggregator re-adopts the root center;
  workers keep training against the refreshed lineage.

Flush policy: a combined commit leaves when every current member has
contributed (fan-in reached) or the accumulation is older than
``flush_interval`` — whichever comes first. Between flushes the
aggregator heartbeats upstream so its root lease never lapses while
workers are slow.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import numpy as np

from distkeras_tpu.netps.errors import NetPSError
from distkeras_tpu.netps.fold import (check_discipline, counter_scalar,
                                      decode_entry)
from distkeras_tpu.netps.server import PSServer
from distkeras_tpu.netps.shards import make_ps_client
from distkeras_tpu.runtime import config
from distkeras_tpu.telemetry import tracing


#: the per-shard -> scalar MIN reduction now lives with the rest of the
#: counter rules in ``netps.fold`` (shared with the fleet simulator);
#: kept under its old private name for this module's call sites.
_counter_scalar = counter_scalar

#: default seconds an under-fan-in accumulation may age before it is
#: flushed anyway (a straggler must not hold the whole host's progress).
_FLUSH_INTERVAL_S = 0.02


class AggregatorServer(PSServer):
    """A per-host pre-combining parameter server (see module docstring).

    ``upstream`` is the root's endpoint; ``init`` seeds an uninitialized
    root (the aggregator joins upstream as ONE worker and adopts the
    root's center + counter). Everything a PSServer accepts — discipline,
    lease, transport (shm ring included) — applies to the local side.
    """

    def __init__(self, upstream: str,
                 init: Optional[Sequence[np.ndarray]] = None,
                 discipline: str = "adag", host: str = "127.0.0.1",
                 port: int = 0, lease_s: Optional[float] = None,
                 transport: Optional[str] = None,
                 flush_interval: float = _FLUSH_INTERVAL_S,
                 fan_in: Optional[int] = None,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None,
                 state_dir: Optional[str] = None,
                 snapshot_every: Optional[int] = None,
                 epoch: int = 0):
        # Validate BEFORE the upstream join (a bad discipline/transport
        # must not leak a phantom root membership); the PSClient ctor
        # validates the transport.
        check_discipline(discipline)
        # Before super().__init__: a fresh state dir snapshots from the
        # PSServer ctor, and this class's snapshot override reads the
        # absorb cursor.
        self._absorbs = 0
        # The factory: a sharded root (``;`` endpoint matrix) gets a
        # ShardedPSClient — the aggregator is then the ONE sharding-aware
        # hop on this host, and its local workers stay plain.
        self._up = make_ps_client(upstream, timeout=timeout, retries=retries,
                                  backoff=backoff, transport=transport)
        try:
            center, updates = self._up.join(init=list(init or ()))
            updates = _counter_scalar(updates)
            super().__init__(center=center, discipline=discipline,
                             host=host, port=port, lease_s=lease_s,
                             transport=transport, state_dir=state_dir,
                             snapshot_every=snapshot_every, epoch=epoch)
        except BaseException:
            try:
                self._up.leave()
            except Exception:  # noqa: BLE001 - best effort on teardown
                pass
            self._up.close()
            raise
        if state_dir:
            # The recovered update counter IS the absorb cursor: the
            # aggregator journals/replicates per absorbed window (the
            # root-lineage counter only advances on re-pull, so it cannot
            # index the journal). Resume the cursor from the journal...
            self._absorbs = int(self._updates)
            # PSServer recovery adopted the DISK center + counter — right
            # for a root, wrong here: an aggregator's center is the root's
            # (just re-pulled via the join above) and its counter is in
            # root units. Keep recovery's dedup table/epoch/commits_total
            # (a restarted aggregator must still dedup its children's
            # retransmits) and restore the upstream view.
            self._center = [np.array(a, np.float32) for a in center]
        self._updates = int(updates)  # root-lineage counter, not local
        self.upstream = upstream
        self.flush_interval = float(flush_interval)
        self.fan_in = fan_in
        self._init_absorb_state()
        self._flush_cv = threading.Condition(self._lock)
        self._flusher_thread: Optional[threading.Thread] = None

    def _init_absorb_state(self) -> None:
        """The combined-window accumulator + its accounting, factored out
        so a tree node's warm standby (a :class:`~distkeras_tpu.netps.
        server.PSServer` by construction, an aggregator only after it
        promotes) can arm the same absorb machinery without this class's
        ctor (which dials upstream eagerly)."""
        if not hasattr(self, "_absorbs"):
            self._absorbs = 0
        #: accumulated (decoded f32) combined delta + its min pull counter.
        self._acc: Optional[list] = None
        self._acc_pulled: Optional[int] = None
        self._acc_count = 0
        #: DISTINCT contributors to the open window — the fan-in check
        #: counts members heard from, not commits (an overlapping worker
        #: can land 2 commits while others landed none).
        self._acc_members: set = set()
        #: constituent trace ids of the open window (traced commits only):
        #: the flush's ``hier.flush`` span links them, so a worker's
        #: commit trace connects to the combined upstream commit's.
        self._acc_traces: list = []
        #: constituent (wid, seq) identities of the open window — a lost
        #: window's ``netps_lost_window`` event names exactly which
        #: workers' commits died with it (bounded like the trace links).
        self._acc_pairs: list = []
        self._acc_t0 = 0.0
        #: combined commits forwarded upstream / worker commits absorbed —
        #: forwarded/absorbed is the measured root-ingress cut.
        self.forwarded = 0
        self.absorbed = 0
        #: worker windows inside forwarded combined commits (constituent
        #: count, not combined count) — with lost/dropped/buffered these
        #: make the window-conservation ledger the tree stats expose.
        self.forwarded_commits = 0
        self.lost_windows = 0
        self.lost_commits = 0

    # ------------------------------------------------------------------
    def start(self) -> "AggregatorServer":
        if self._started:
            return self
        super().start()
        t = threading.Thread(target=self._flusher_loop,
                             name="netps-hier-flush")
        t.start()
        self._flusher_thread = t
        return self

    def close(self) -> None:
        """Drain local commits, stop the server, then flush the remainder
        upstream and leave — the root holds every absorbed commit before
        this returns, except windows lost to an upstream eviction or an
        upstream outage outlasting the retry budget, which are counted in
        :attr:`lost_windows` (never silently dropped)."""
        self.drain()
        super().close()  # joins handlers: no new local commits past here
        t = self._flusher_thread
        if t is not None:
            t.join()
        self._flush_once(force=True)  # accounts its own failures
        try:
            self._up.leave()
        except (NetPSError, OSError):
            pass
        self._up.close()

    # ------------------------------------------------------------------
    def set_fan_in(self, fan_in: Optional[int]) -> None:
        """Retune the flush fan-in mid-run (the tuner's HIER lever):
        ``None`` restores combine-the-full-membership; ``1`` degrades the
        aggregator to a pass-through forwarder (flush per commit) — the
        flat-topology behavior without tearing a single connection down.
        Wakes the flusher so a now-satisfied window flushes immediately;
        open-window accounting is untouched (exactly-once holds)."""
        with self._flush_cv:
            self.fan_in = fan_in
            self._flush_cv.notify_all()

    # ------------------------------------------------------------------
    def _fold_locked(self, wid: int, seq: int, pulled, delta: list) -> int:
        """Absorb one worker commit (lock held): decode wire-domain
        entries, add into the combined accumulator, take the min pull
        counter, and do the usual exactly-once bookkeeping — but do NOT
        advance the update counter (it mirrors the root lineage) and do
        NOT touch the center (the root owns it)."""
        pulled = int(pulled)
        staleness = self._updates - pulled
        with tracing.child_scope("commit.fold", wid=wid, seq=seq,
                                 hier=True):
            dec = [np.asarray(decode_entry(e), np.float32) for e in delta]
            if self._acc is None:
                self._acc = [a.copy() for a in dec]
                self._acc_pulled = pulled
                self._acc_t0 = time.monotonic()
            else:
                for acc, a in zip(self._acc, dec):
                    acc += a
                self._acc_pulled = min(self._acc_pulled, pulled)
        ctx = tracing.current()
        if ctx is not None and len(self._acc_traces) < 64:
            self._acc_traces.append(ctx.trace)
        self._acc_count += 1
        self._acc_members.add(wid)
        if len(self._acc_pairs) < 512:
            self._acc_pairs.append((wid, seq))
        self.absorbed += 1
        self.commit_log.append((wid, seq, staleness))
        self._last_seq[wid] = seq
        self.commits_total += 1
        # Durability tail, absorb-order = journal order (the root folds
        # against the update counter; an aggregator journals/replicates
        # against its absorb cursor — see ``_absorbs``). A storeless,
        # standby-less aggregator pays nothing here.
        u = self._absorbs
        self._absorbs += 1
        if self._repl_on:
            rec = {"u": u, "wid": wid, "seq": seq, "st": staleness,
                   "e": self.epoch, "n": self.commits_total,
                   "delta": list(delta)}
            if ctx is not None:
                rec["tr"] = ctx.trace
            self._repl.append(rec)
        if self._store is not None:
            with tracing.child_scope("commit.fsync", wid=wid, seq=seq):
                self._store.append(epoch=self.epoch, wid=wid, seq=seq,
                                   staleness=staleness, updates=u,
                                   commits_total=self.commits_total,
                                   delta=delta)
                if self._store.due(self._absorbs):
                    self._snapshot_locked()
        # The same month-long-run bound the root server keeps: the
        # aggregator's absorbed-commit evidence must not grow without
        # limit either (len + dropped == commits_total holds here too).
        self._trim_log_locked(2 * self._log_keep)
        self._purge_pending(wid, below_seq=seq)
        self._flush_cv.notify_all()
        return staleness

    def _repl_cursor_locked(self) -> int:
        # Replication (and with it a warm standby's tail) advances by the
        # absorb cursor, not the root-lineage update counter.
        return self._absorbs

    def _snapshot_locked(self) -> None:
        # The snapshot cursor must line up with the journal's ``u``
        # fields — the absorb cursor, not the root-lineage counter. The
        # center snapshotted is the adopted root center: a restarted
        # aggregator's recovery base until it re-pulls upstream.
        self._store.snapshot(center=self._center, updates=self._absorbs,
                             last_seq=self._last_seq, epoch=self.epoch,
                             commits_total=self.commits_total)
        self.snapshots_written += 1
        self._trim_log_locked(self._log_keep + 1)

    # ------------------------------------------------------------------
    def _take_acc_locked(self, force: bool):
        fan = self.fan_in if self.fan_in else max(1, len(self._members))
        age = (time.monotonic() - self._acc_t0) if self._acc_count else 0.0
        if not self._acc_count:
            return None
        if (not force and len(self._acc_members) < fan
                and age < self.flush_interval):
            return None
        taken = (self._acc, self._acc_pulled, self._acc_count,
                 len(self._acc_members), self._acc_traces, self._acc_pairs)
        self._acc = None
        self._acc_pulled = None
        self._acc_count = 0
        self._acc_members = set()
        self._acc_traces = []
        self._acc_pairs = []
        return taken

    def _lose_window(self, pairs: Sequence = (), count: int = 1) -> None:
        """One combined window died (in flight, or landed evicted): count
        it AND name its constituents — the flight recorder must show which
        workers' (wid, seq) windows died, not just that one did."""
        from distkeras_tpu import telemetry

        self.lost_windows += 1
        self.lost_commits += int(count)
        telemetry.counter("netps.hier.lost_windows").add(1)
        telemetry.event("netps_lost_window", {
            "count": int(count),
            "windows": [[int(w), int(s)] for w, s in pairs]})

    def _flush_once(self, force: bool) -> bool:
        """Forward the accumulated combined commit upstream (outside the
        lock) and re-adopt the root's center + counter. Returns whether a
        flush was attempted. Never raises for upstream failures — each
        outcome is accounted exactly once: a commit that dies in flight or
        lands evicted is ONE lost window; a pull failure after a landed
        commit is NOT a lost window (the fold happened; the re-sync just
        waits for the next flush)."""
        from distkeras_tpu import telemetry

        with self._lock:
            taken = self._take_acc_locked(force)
        if taken is None:
            return False
        acc, pulled, count, members, traces, pairs = taken
        try:
            # The combined commit gets its own trace, LINKING the
            # constituent worker traces (a fan-in is a DAG, not a tree —
            # links are how one upstream fold connects to N origins).
            with tracing.trace_scope("hier.flush", count=count,
                                     links=traces[:16]):
                res = self._up.commit(acc, pulled)
        except (NetPSError, OSError):
            # Past the client's own retry budget: the combined window died
            # in flight — the flat topology's lost-commit semantics, one
            # level up.
            self._lose_window(pairs, count)
            return True
        if res.evicted:
            # The aggregator's root lease lapsed with this window pending:
            # the combined commit was discarded upstream. The client
            # already re-joined; fall through to re-adopt.
            self._lose_window(pairs, count)
        else:
            self.forwarded += 1
            self.forwarded_commits += count
            telemetry.counter("netps.hier.combined_commits").add(1)
            telemetry.counter("netps.hier.worker_commits").add(count)
            # Distinct contributors, not commit count — an overlapping
            # worker's double commit must not read as wider fan-in.
            telemetry.gauge("netps.hier.fan_in").set(float(members))
        try:
            center, updates = self._up.pull()
        except (NetPSError, OSError):
            return True  # commit already accounted; re-sync next flush
        with self._lock:
            self._center = [np.asarray(a, np.float32) for a in center]
            self._updates = _counter_scalar(updates)
        return True

    def _flusher_loop(self) -> None:
        lease = self._up.lease_s or config.env_float("DKTPU_PS_LEASE")
        # The between-flush heartbeat only fires after a wait returns, so
        # the wait must never outlast the renewal deadline: a
        # flush_interval above lease/3 would let the root lease lapse
        # across an idle stretch and the NEXT combined window land
        # evicted — a lost window with no fault anywhere.
        wait_s = self.flush_interval
        if lease:
            wait_s = min(wait_s, max(0.001, float(lease) / 3.0))
        last_rpc = time.monotonic()
        while not self._stop.is_set():
            with self._flush_cv:
                self._flush_cv.wait(wait_s)
            if self._flush_once(force=False):
                last_rpc = time.monotonic()
            elif time.monotonic() - last_rpc > float(lease) / 3.0:
                try:
                    self._up.heartbeat()
                except (NetPSError, OSError):
                    pass  # lease renewal is best-effort between flushes
                last_rpc = time.monotonic()
