"""CLI: run a standalone netps parameter server.

``Job``/``Punchcard`` launch this on the PS host of a pod::

    python -m distkeras_tpu.netps --host 0.0.0.0 --port 7077 \
        --discipline adag --lease 10

The server starts uninitialized — the first worker's ``join`` seeds the
center with its model parameters, so this process needs no model (or jax)
knowledge. It prints ``NETPS_READY <host:port>`` once listening and runs
until SIGTERM/SIGINT, then drains gracefully (in-flight commits finish,
late clients get a typed ``ServerDrainingError``).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from distkeras_tpu.netps.fold import SUPPORTED_DISCIPLINES
from distkeras_tpu.netps.server import PSServer


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.netps",
        description="Standalone networked parameter server.")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=7077)
    ap.add_argument("--discipline", default="adag",
                    choices=sorted(SUPPORTED_DISCIPLINES))
    ap.add_argument("--lease", type=float, default=None,
                    help="membership lease seconds (default DKTPU_PS_LEASE)")
    args = ap.parse_args(argv)
    server = PSServer(discipline=args.discipline, host=args.host,
                      port=args.port, lease_s=args.lease).start()
    stop = threading.Event()

    def _stop(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    print(f"NETPS_READY {server.endpoint}", flush=True)
    stop.wait()
    server.close()
    print(f"NETPS_DRAINED commits={len(server.commit_log)} "
          f"evictions={server.evictions} rejoins={server.rejoins}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
