"""CLI: run a standalone netps parameter server (primary or warm standby).

``Job``/``Punchcard`` launch this on the PS host of a pod::

    python -m distkeras_tpu.netps --host 0.0.0.0 --port 7077 \
        --discipline adag --lease 10 --state-dir /var/dktpu/ps

The server starts uninitialized — the first worker's ``join`` seeds the
center with its model parameters, so this process needs no model (or jax)
knowledge. With ``--state-dir`` (``DKTPU_PS_STATE_DIR``) every folded
commit is journaled and the center snapshotted (``--snapshot-every`` /
``DKTPU_PS_SNAPSHOT_EVERY``), so a SIGKILLed server relaunched on the same
directory resumes its center, counter, and dedup state. With ``--standby
host:port`` (``DKTPU_PS_STANDBY``) the process runs as a warm standby of
that primary instead: it tails the journal stream, serves nothing until
the primary's lease lapses, then promotes (printing ``NETPS_PROMOTED
epoch=N``) and fences the old lineage.

With ``--upstream host:port`` the process runs as an interior
aggregation-tree node (``TreeNode``) instead: it absorbs its children's
commits, journals them in absorb order, and flushes combined windows
into the upstream — ``--tree-level``/``--tree-group`` locate it in the
``DKTPU_TREE_SPEC`` shape (and key its uplink for ``link_down`` chaos),
``--tree-buffer`` bounds partition ride-through. ``--upstream`` plus
``--standby`` runs the node's region-local warm ``TreeStandby``, which
on promotion fences the dead node AND joins the upstream itself so the
subtree keeps flowing.

It prints ``NETPS_READY <host:port>`` once listening and runs until
SIGTERM/SIGINT, then drains gracefully (in-flight commits finish, late
clients get a typed ``ServerDrainingError``). The FIRST signal prints
``NETPS_DRAINING`` immediately — at signal time, not after the drain — so
a supervisor (``Job.supervise``) can tell a draining PS from a hung one; a
SECOND signal during the drain force-exits nonzero (status 70) instead of
being silently swallowed.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from distkeras_tpu.netps.fold import SUPPORTED_DISCIPLINES
from distkeras_tpu.netps.server import PSServer
from distkeras_tpu.runtime import config
from distkeras_tpu.telemetry import tracing

#: exit status of a second-signal forced abort (EX_SOFTWARE; distinct from
#: both a clean drain's 0 and a SIGKILL's -9 so ``Job.supervise`` can tell
#: the three apart).
ABORT_STATUS = 70


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.netps",
        description="Standalone networked parameter server.")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=7077)
    ap.add_argument("--discipline", default="adag",
                    choices=sorted(SUPPORTED_DISCIPLINES))
    ap.add_argument("--lease", type=float, default=None,
                    help="membership lease seconds (default DKTPU_PS_LEASE)")
    ap.add_argument("--state-dir", default=None,
                    help="durable journal+snapshot directory (default "
                         "DKTPU_PS_STATE_DIR; empty = in-memory only)")
    ap.add_argument("--snapshot-every", type=int, default=None,
                    help="folds between center snapshots (default "
                         "DKTPU_PS_SNAPSHOT_EVERY)")
    ap.add_argument("--standby", metavar="HOST:PORT", default=None,
                    help="run as a warm standby of this primary (default "
                         "DKTPU_PS_STANDBY; empty = run as a primary)")
    ap.add_argument("--promote-after", type=float, default=None,
                    help="seconds of primary silence before a standby "
                         "promotes itself (default: the lease)")
    ap.add_argument("--shard", metavar="K/N", default=None,
                    help="serve shard K of an N-shard center (0-based); "
                         "the partition plan is adopted from the first "
                         "join (and persisted under --state-dir). Applies "
                         "to primaries and standbys alike.")
    ap.add_argument("--upstream", metavar="HOST:PORT[,...]", default=None,
                    help="run as an interior aggregation-tree node that "
                         "absorbs its children's commits and flushes "
                         "combined windows into this upstream (comma list "
                         "= failover walk). With --standby, run as that "
                         "tree node's warm TreeStandby instead.")
    ap.add_argument("--tree-level", type=int, default=0,
                    help="this node's level in DKTPU_TREE_SPEC / "
                         "--tree-spec (0 = leaf-most interior level)")
    ap.add_argument("--tree-group", type=int, default=0,
                    help="this node's group index within its level")
    ap.add_argument("--tree-spec", default=None,
                    help="bottom-up tree grammar name:fanout[:codec],... "
                         "(default DKTPU_TREE_SPEC)")
    ap.add_argument("--tree-buffer", type=int, default=None,
                    help="partition ride-through bound in combined "
                         "windows (default DKTPU_TREE_BUFFER)")
    ap.add_argument("--fan-in", type=int, default=None,
                    help="tree node flush fan-in (default: full local "
                         "membership)")
    ap.add_argument("--flush-interval", type=float, default=None,
                    help="tree node max window age (seconds) before an "
                         "undersized window flushes anyway")
    args = ap.parse_args(argv)
    shard_index = shard_count = None
    if args.shard:
        try:
            k, n = args.shard.split("/", 1)
            shard_index, shard_count = int(k), int(n)
        except ValueError:
            ap.error(f"--shard must be K/N (got {args.shard!r})")
        if not 0 <= shard_index < shard_count:
            ap.error(f"--shard {args.shard}: K must be in 0..N-1")
    state_dir = (args.state_dir if args.state_dir is not None
                 else config.env_str("DKTPU_PS_STATE_DIR") or None)
    standby_of = (args.standby if args.standby is not None
                  else config.env_str("DKTPU_PS_STANDBY") or None)
    tree_spec = (args.tree_spec if args.tree_spec is not None
                 else config.env_str("DKTPU_TREE_SPEC") or None)
    if args.upstream and shard_index is not None:
        ap.error("--shard and --upstream are mutually exclusive: an "
                 "interior tree node is never itself a shard (shard the "
                 "ROOT and point --upstream at the `;` matrix instead)")
    kw = dict(discipline=args.discipline, host=args.host, port=args.port,
              lease_s=args.lease, state_dir=state_dir,
              snapshot_every=args.snapshot_every)
    if not args.upstream:
        kw.update(shard_index=shard_index, shard_count=shard_count)
    # Label this process for the trace/flight streams (an explicit
    # DKTPU_TRACE_ROLE — e.g. one the fleet launcher stamped — wins) and
    # arm the crash-path flight-recorder dump before anything can fail.
    if args.upstream and standby_of:
        tracing.set_role(f"tree{args.tree_level}g{args.tree_group}-standby")
    elif args.upstream:
        tracing.set_role(f"tree{args.tree_level}g{args.tree_group}")
    elif standby_of:
        tracing.set_role("standby")
    elif shard_index is not None:
        tracing.set_role(f"shard{shard_index}")
    else:
        tracing.set_role("ps")
    tracing.install_crash_hooks()
    from distkeras_tpu.telemetry.vitals import start_vitals

    start_vitals()  # no-op unless DKTPU_VITALS_S is set
    tree_kw = dict(level=args.tree_level, group=args.tree_group,
                   spec=tree_spec, buffer_windows=args.tree_buffer,
                   fan_in=args.fan_in)
    if args.flush_interval is not None:
        tree_kw["flush_interval"] = args.flush_interval
    if args.upstream and standby_of:
        from distkeras_tpu.netps.tree import TreeStandby

        server = TreeStandby(standby_of, upstream=args.upstream,
                             promote_after=args.promote_after,
                             **tree_kw, **kw).start()
    elif args.upstream:
        from distkeras_tpu.netps.tree import TreeNode

        server = TreeNode(args.upstream, **tree_kw, **kw).start()
    elif standby_of:
        from distkeras_tpu.netps.standby import StandbyServer

        server = StandbyServer(standby_of,
                               promote_after=args.promote_after,
                               **kw).start()
    else:
        server = PSServer(**kw).start()
    stop = threading.Event()
    signals_seen = [0]

    def _stop(signum, frame):
        signals_seen[0] += 1
        if signals_seen[0] == 1:
            # Printed AT SIGNAL TIME (os.write: async-signal-safe, no
            # buffering), before the drain starts — a supervisor watching
            # stdout can distinguish "draining, give it a moment" from
            # "hung, escalate" without guessing.
            os.write(1, b"NETPS_DRAINING\n")
            stop.set()
            # Dump the flight ring while the process is still healthy —
            # the drain may take seconds and a second signal force-exits
            # without running atexit. No-op with tracing off; dedup'd per
            # reason, so a SIGTERM storm writes the ring once.
            tracing.flight_dump("sigterm")
        else:
            # A second signal mid-drain means the operator (or Job.kill's
            # escalation) wants OUT — force-exit nonzero rather than
            # letting _stop silently swallow it while close() blocks on a
            # wedged handler thread.
            os.write(1, b"NETPS_ABORTED\n")
            os._exit(ABORT_STATUS)

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    print(f"NETPS_READY {server.endpoint}", flush=True)
    announced = False
    while not stop.wait(0.2):
        if (not announced and getattr(server, "promoted", False)):
            announced = True
            print(f"NETPS_PROMOTED epoch={server.epoch}", flush=True)
    server.close()
    trace_d = tracing.trace_dir()
    if trace_d:
        # Final telemetry dump beside the trace stream: the collector
        # merges this process's counters/events into the fleet timeline.
        from distkeras_tpu import telemetry

        try:
            os.makedirs(trace_d, exist_ok=True)
            telemetry.write_jsonl(
                telemetry.get(),
                os.path.join(trace_d,
                             f"telemetry-{tracing.role()}-{os.getpid()}"
                             ".jsonl"))
        except OSError:
            pass
    print(f"NETPS_DRAINED commits={server.commits_total} "
          f"epoch={server.epoch} snapshots={server.snapshots_written} "
          f"evictions={server.evictions} rejoins={server.rejoins}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
