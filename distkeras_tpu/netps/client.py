"""The hardened parameter-server client: every edge guarded.

Where the reference's worker did ``socket.connect(); send(pickle)`` and
hoped, every RPC here has

* a **deadline** — ``DKTPU_NET_TIMEOUT`` seconds per attempt, covering
  connect, send, and the full reply;
* **bounded retries with exponential backoff + full jitter** —
  ``DKTPU_NET_RETRIES`` attempts spaced by
  :func:`~distkeras_tpu.resilience.backoff.full_jitter` over a
  ``DKTPU_NET_BACKOFF``-based envelope, so W workers cut off by the same
  partition do not retry in lockstep;
* **idempotent commit sequencing** — the client assigns ``(worker_id,
  seq)`` *before* the first send and reuses it on every retransmit, so a
  commit whose ACK was lost is folded exactly once (the server dedups and
  answers ``duplicate=True``);
* **automatic re-join** — an RPC rejected with ``lease_expired`` (the
  server evicted us while we were away) triggers a fresh ``join``; ``pull``
  then simply returns the re-joined center, while ``commit`` reports
  ``evicted=True`` so the worker loop discards its stale window and
  continues from a fresh pull.

A failed attempt always tears the connection down and reconnects — stale
bytes die with the old socket, and the ``req`` id echo discards any
duplicate replies that survive on a healthy one. Typed, **non-retryable**
failures (:class:`ServerDrainingError`, :class:`LeaseExpiredError`)
surface immediately.

One client serves one worker thread; it is deliberately not thread-safe
(the reference's one-socket-per-worker layout).
"""

from __future__ import annotations

import socket
import time
from typing import NamedTuple, Optional, Sequence

import numpy as np

from distkeras_tpu.netps import wire
from distkeras_tpu.netps.errors import (
    LeaseExpiredError,
    NetPSError,
    ProtocolError,
    RPCTimeoutError,
    ServerClosedError,
    ServerDrainingError,
)
from distkeras_tpu.resilience.backoff import full_jitter
from distkeras_tpu.runtime import config

#: server error kind -> typed exception. Everything here is NON-retryable:
#: the server answered, it just said no.
_ERROR_TYPES = {
    "draining": ServerDrainingError,
    "lease_expired": LeaseExpiredError,
    "uninitialized": NetPSError,
    "protocol": ProtocolError,
}


class CommitResult(NamedTuple):
    """What happened to one commit: ``applied`` (folded now),
    ``duplicate`` (folded by an earlier retransmit — still success),
    ``evicted`` (lease expired; the window was discarded and the client
    re-joined — pull fresh and continue)."""

    applied: bool
    duplicate: bool
    evicted: bool
    updates: int
    staleness: int


class PSClient:
    """One worker's connection to a :class:`~distkeras_tpu.netps.server.
    PSServer` (or anything speaking the wire protocol, e.g. the chaos
    proxy). ``timeout``/``retries``/``backoff`` default from the registry
    (`DKTPU_NET_TIMEOUT` / `DKTPU_NET_RETRIES` / `DKTPU_NET_BACKOFF`)."""

    def __init__(self, endpoint: str, worker_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None,
                 auto_rejoin: bool = True):
        self._host, self._port = wire.split_endpoint(endpoint)
        self.endpoint = endpoint
        self.worker_id = worker_id
        self.timeout = float(timeout if timeout is not None
                             else config.env_float("DKTPU_NET_TIMEOUT"))
        self.retries = int(retries if retries is not None
                           else config.env_int("DKTPU_NET_RETRIES"))
        self.backoff = float(backoff if backoff is not None
                             else config.env_float("DKTPU_NET_BACKOFF"))
        self.auto_rejoin = auto_rejoin
        self.lease_s: Optional[float] = None
        self._sock: Optional[socket.socket] = None
        self._req = 0
        self._seq = -1
        self._closed = False
        self._ever_connected = False
        #: times this client re-joined after an eviction (worker loops
        #: watch it to re-adopt the center on rejoin).
        self.rejoin_count = 0

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._closed = True
        self._disconnect()

    def __enter__(self) -> "PSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _connect(self, deadline: float) -> socket.socket:
        if self._sock is not None:
            return self._sock
        from distkeras_tpu import telemetry

        if self._ever_connected:
            telemetry.counter("netps.reconnects").add(1)
        # The connect spends from the SAME per-attempt budget as the send
        # and reply (the documented contract): against a SYN-blackholing
        # partition, connect-then-wait must not cost 2x the deadline.
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded before connect")
        sock = socket.create_connection((self._host, self._port),
                                        timeout=remaining)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._ever_connected = True
        return sock

    def _disconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- the guarded RPC core ----------------------------------------------
    def _rpc(self, op: str, header: dict,
             arrays: Sequence[np.ndarray] = ()) -> tuple[dict, list]:
        if self._closed:
            raise ServerClosedError(f"client to {self.endpoint} is closed")
        from distkeras_tpu import telemetry

        attempts = self.retries + 1
        last_exc: Optional[BaseException] = None
        with telemetry.span(f"netps.rpc.{op}"):
            for attempt in range(attempts):
                self._req += 1
                req = self._req
                hdr = dict(header, op=op, req=req)
                if self.worker_id is not None:
                    hdr.setdefault("worker_id", int(self.worker_id))
                try:
                    return self._attempt(req, hdr, arrays)
                except (socket.timeout, ConnectionError, OSError,
                        ProtocolError) as e:
                    last_exc = e
                    self._disconnect()
                    if attempt + 1 < attempts:
                        telemetry.counter("netps.retries").add(1)
                        time.sleep(full_jitter(self.backoff, attempt))
        telemetry.counter("netps.rpc_failures").add(1)
        raise RPCTimeoutError(
            f"{op} to {self.endpoint} failed after {attempts} attempts "
            f"(last: {type(last_exc).__name__}: {last_exc})",
            attempts=attempts)

    def _attempt(self, req: int, hdr: dict,
                 arrays: Sequence[np.ndarray]) -> tuple[dict, list]:
        """One connect + send + matched-reply receive under ONE deadline."""
        from distkeras_tpu import telemetry

        deadline = time.monotonic() + self.timeout
        sock = self._connect(deadline)
        sock.settimeout(max(0.001, deadline - time.monotonic()))
        sent = wire.send_frame(sock, wire.KIND_REQUEST, hdr, arrays)
        telemetry.counter("netps.bytes_sent").add(sent)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(f"{hdr['op']} deadline exceeded")
            sock.settimeout(remaining)
            raw = wire.read_raw_frame(sock)
            kind, rhdr, rarrays = wire.decode_frame(raw)
            if kind != wire.KIND_REPLY:
                raise ProtocolError(f"expected a reply frame, got kind {kind}")
            if rhdr.get("req") != req:
                # A duplicated or late reply (chaos `dup`): discard and keep
                # reading — the req echo is what keeps the stream sane.
                telemetry.counter("netps.stale_replies").add(1)
                continue
            telemetry.counter("netps.bytes_received").add(len(raw))
            err = rhdr.get("error")
            if err:
                exc = _ERROR_TYPES.get(err, NetPSError)
                raise exc(f"{hdr['op']}: server said {err}: "
                          f"{rhdr.get('message', '')}")
            return rhdr, rarrays

    # -- RPC surface --------------------------------------------------------
    def join(self, init: Optional[Sequence[np.ndarray]] = None,
             ) -> tuple[list, int]:
        """Become (or re-become) a member; returns ``(center, updates)``.
        ``init`` seeds an uninitialized server (first joiner wins; later
        inits are ignored — everyone adopts the server's center)."""
        hdr, center = self._rpc("join", {}, list(init or ()))
        self.worker_id = int(hdr["worker_id"])
        self.lease_s = hdr.get("lease_s")
        # Resume the commit sequence past what the server already folded
        # from this worker_id: a restarted worker process starts at seq -1,
        # and without adopting the server's high-water mark every commit of
        # the new incarnation would be deduped away as a "retransmit".
        server_seq = int(hdr.get("last_seq", -1))
        if server_seq > self._seq:
            self._seq = server_seq
        return center, int(hdr["updates"])

    def pull(self) -> tuple[list, int]:
        """Current center + update counter; renews the lease. An evicted
        client transparently re-joins first (``auto_rejoin``)."""
        try:
            hdr, center = self._rpc("pull", {})
        except LeaseExpiredError:
            if not self.auto_rejoin:
                raise
            self.rejoin_count += 1
            return self.join()
        return center, int(hdr["updates"])

    def commit(self, delta: Sequence[np.ndarray],
               pulled_counter: int) -> CommitResult:
        """Fold ``delta`` (worker-normalized) into the center. The seq is
        assigned before the first transmission and reused across retries:
        a lost ACK can never double-fold."""
        self._seq += 1
        seq = self._seq
        try:
            hdr, _ = self._rpc(
                "commit", {"seq": seq, "pulled": int(pulled_counter)},
                list(delta))
        except LeaseExpiredError:
            if not self.auto_rejoin:
                raise
            self.rejoin_count += 1
            self.join()
            return CommitResult(applied=False, duplicate=False, evicted=True,
                                updates=-1, staleness=-1)
        return CommitResult(
            applied=bool(hdr.get("applied")),
            duplicate=bool(hdr.get("duplicate")),
            evicted=False, updates=int(hdr["updates"]),
            staleness=int(hdr.get("staleness", -1)))

    def heartbeat(self) -> int:
        """Renew the lease; returns the server's update counter."""
        try:
            hdr, _ = self._rpc("heartbeat", {})
        except LeaseExpiredError:
            if not self.auto_rejoin:
                raise
            self.rejoin_count += 1
            _center, updates = self.join()
            return updates
        return int(hdr["updates"])

    def leave(self) -> None:
        """Best-effort clean departure (a dead server is not an error —
        leaving was the goal)."""
        try:
            self._rpc("leave", {})
        except (NetPSError, OSError):
            pass
