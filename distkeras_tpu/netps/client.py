"""The hardened parameter-server client: every edge guarded, fast by default.

Where the reference's worker did ``socket.connect(); send(pickle)`` and
hoped, every RPC here has

* a **deadline** — ``DKTPU_NET_TIMEOUT`` seconds per attempt, covering
  connect, send, and the full reply;
* **bounded retries with exponential backoff + full jitter** —
  ``DKTPU_NET_RETRIES`` attempts spaced by
  :func:`~distkeras_tpu.resilience.backoff.full_jitter` over a
  ``DKTPU_NET_BACKOFF``-based envelope, so W workers cut off by the same
  partition do not retry in lockstep;
* **idempotent commit sequencing** — the client assigns ``(worker_id,
  seq)`` *before* the first send and reuses it on every retransmit, so a
  commit whose ACK was lost is folded exactly once (the server dedups and
  answers ``duplicate=True``);
* **automatic re-join** — an RPC rejected with ``lease_expired`` (the
  server evicted us while we were away) triggers a fresh ``join``; ``pull``
  then simply returns the re-joined center, while ``commit`` reports
  ``evicted=True`` so the worker loop discards its stale window and
  continues from a fresh pull.

The data plane on top of those guarantees (all capability-negotiated at
join through the server's advertised :data:`~distkeras_tpu.netps.wire.CAPS`
— a PR 4 peer is spoken to in the PR 4 dialect):

* **Compressed deltas** (``DKTPU_NET_COMPRESS=bf16|int8``): commit tensors
  are quantized per-tensor before transmission; under ``int8`` the
  quantization error is carried forward as an **error-feedback residual**
  (added to the next window's delta), so the bias a 4x-smaller wire
  introduces is corrected over rounds instead of accumulating. The
  residual is discarded on rejoin — it belongs to the discarded window
  lineage.
* **Sharded striping** (``DKTPU_NET_SHARDS=N``): the parameter tree's
  tensors are striped (byte-balanced, deterministic) across N connections;
  pulls and commits issue one concurrent sub-RPC per stripe and reassemble
  before the caller sees anything. One logical commit keeps ONE ``seq``
  across all stripes — the server assembles the stripes and folds exactly
  once. A striped pull whose stripes straddled a concurrent fold (torn
  read) is detected by the echoed update counters and re-pulled; after
  ``_PULL_CONSISTENT_TRIES`` misses it falls back to one unsharded pull.

A failed attempt always tears that connection down and reconnects — stale
bytes die with the old socket, and the ``req`` id echo discards any
duplicate replies that survive on a healthy one. Typed, **non-retryable**
failures (:class:`ServerDrainingError`, :class:`LeaseExpiredError`)
surface immediately.

One client serves one worker thread; public methods are not safe to call
concurrently (the striped sub-RPCs inside one call run on the client's own
pool over disjoint connections — that is the supported concurrency).
"""

from __future__ import annotations

import socket
import threading
import time
import warnings
from concurrent.futures import ThreadPoolExecutor
from typing import NamedTuple, Optional, Sequence

import numpy as np

from distkeras_tpu.netps import mesh as _mesh
from distkeras_tpu.netps import shm, wire
from distkeras_tpu.netps.endpoints import EndpointWalker, budget_left
from distkeras_tpu.netps.errors import (
    EpochFencedError,
    LeaseExpiredError,
    NetPSError,
    NotPrimaryError,
    ProtocolError,
    RPCTimeoutError,
    ServerClosedError,
    ServerDrainingError,
    ShardPlanError,
)
from distkeras_tpu.resilience.backoff import full_jitter
from distkeras_tpu.runtime import config
from distkeras_tpu.telemetry import tracing
from distkeras_tpu.telemetry.tracing import clock as _traceclock

#: server error kind -> typed exception. Everything here except
#: ``not_primary`` is NON-retryable: the server answered, it just said no.
#: ``not_primary`` (an unpromoted standby / a fenced ex-primary) is
#: retryable *by walking the endpoint list* — the same RPC against the
#: next endpoint can succeed, so ``_rpc`` treats it like a transport
#: failure. ``epoch_fenced`` surfaces typed: the caller re-joins (walking
#: to the promoted primary) and discards its stale window, exactly like an
#: eviction.
_ERROR_TYPES = {
    "draining": ServerDrainingError,
    "lease_expired": LeaseExpiredError,
    "uninitialized": NetPSError,
    "protocol": ProtocolError,
    "epoch_fenced": EpochFencedError,
    "not_primary": NotPrimaryError,
    "shard_plan": ShardPlanError,
}

#: striped-pull consistency budget: whole-pull re-reads before falling back
#: to one unsharded pull (a torn read needs a fold to land mid-pull — rare).
_PULL_CONSISTENT_TRIES = 3


class CommitResult(NamedTuple):
    """What happened to one commit: ``applied`` (folded now),
    ``duplicate`` (folded by an earlier retransmit — still success),
    ``evicted`` (lease expired; the window was discarded and the client
    re-joined — pull fresh and continue)."""

    applied: bool
    duplicate: bool
    evicted: bool
    updates: int
    staleness: int


class _Conn:
    """One data connection — TCP socket or shared-memory ring — with its
    own request-id stream (reply matching is per-connection, so ids need
    only be unique per stream)."""

    __slots__ = ("sock", "ring", "req", "ever_connected", "dialect")

    def __init__(self):
        self.sock: Optional[socket.socket] = None
        self.ring: Optional[shm.ShmConnection] = None
        self.req = 0
        self.ever_connected = False
        #: last dialect ESTABLISHED on this conn ("tcp"/"shm"/None): only a
        #: same-dialect re-establishment is failure evidence — a negotiated
        #: dialect switch (the post-join shm upgrade, a fallback's TCP
        #: attach) must not read as a flapping network in telemetry.
        self.dialect: Optional[str] = None


#: measured-bad knob pairings (the PR 6 bench rules, enforced at init
#: instead of living only in docs): (condition-name, why). Warned once
#: per process per combo — a fleet of workers must not scream N times.
_BAD_KNOB_COMBOS_WARNED: set = set()


def _validate_knob_combo(codec: str, transport: str, shards: int) -> None:
    """One-time warning + telemetry event when a measured-bad pairing is
    forced. Purely advisory: the knobs still apply exactly as requested —
    the user may know something the bench did not."""
    combos = []
    if transport == "shm" and codec == wire.CODEC_INT8:
        combos.append((
            "int8+shm",
            "int8 loses on the shm ring: the quantize/dequantize passes "
            "cost more than the bytes they save at memcpy speed "
            "(docs/PERFORMANCE.md); prefer DKTPU_NET_COMPRESS=none"))
    if transport == "shm" and shards > 1:
        combos.append((
            "shards>1+shm",
            "striping over the shm ring pays a doorbell per stripe for "
            "payloads that already move at memcpy speed; prefer "
            "DKTPU_NET_SHARDS=1"))
    if transport == "mesh" and codec == wire.CODEC_INT8:
        combos.append((
            "int8+mesh",
            "the mesh dialect moves zero wire bytes, so the int8 codec "
            "buys nothing and still pays the quantization error plus the "
            "encode/decode passes; prefer DKTPU_NET_COMPRESS=none"))
    if transport == "mesh" and shards > 1:
        combos.append((
            "shards>1+mesh",
            "striping splits commits across sockets the mesh dialect "
            "never opens — every stripe lands on the same in-process "
            "dispatch and the server just reassembles them; prefer "
            "DKTPU_NET_SHARDS=1"))
    for combo, why in combos:
        if combo in _BAD_KNOB_COMBOS_WARNED:
            continue
        _BAD_KNOB_COMBOS_WARNED.add(combo)
        from distkeras_tpu import telemetry

        telemetry.counter("tuner.knob_warnings").add(1)
        telemetry.event("netps_knob_warning", {"combo": combo, "why": why})
        warnings.warn(f"measured-bad knob combination {combo}: {why}",
                      RuntimeWarning, stacklevel=3)


class PSClient:
    """One worker's connection(s) to a :class:`~distkeras_tpu.netps.server.
    PSServer` (or anything speaking the wire protocol, e.g. the chaos
    proxy). ``timeout``/``retries``/``backoff``/``shards``/``compress``
    default from the registry (`DKTPU_NET_TIMEOUT` / `DKTPU_NET_RETRIES` /
    `DKTPU_NET_BACKOFF` / `DKTPU_NET_SHARDS` / `DKTPU_NET_COMPRESS`)."""

    def __init__(self, endpoint: str, worker_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None,
                 auto_rejoin: bool = True,
                 shards: Optional[int] = None,
                 compress: Optional[str] = None,
                 transport: Optional[str] = None):
        #: serializes the shm->TCP fallback sweep AND the endpoint walk:
        #: only the stripe thread that actually transitions (walks, or
        #: nulls shm_info) closes the other conns — a second sweeper would
        #: otherwise close a sibling's freshly re-established TCP socket
        #: mid-RPC. Created first so the walker can share it.
        self._fallback_lock = threading.Lock()
        #: ordered failover traversal — ``endpoint`` may be the
        #: comma-separated ``DKTPU_PS_ENDPOINT`` form (primary first, then
        #: standbys); a single endpoint is a one-element list and behaves
        #: exactly as before. Shares the fallback lock: the walk teardown
        #: must not interleave with the shm fallback sweep.
        self._walker = EndpointWalker(endpoint, lock=self._fallback_lock)
        self.endpoint = endpoint
        self.worker_id = worker_id
        self.timeout = float(timeout if timeout is not None
                             else config.env_float("DKTPU_NET_TIMEOUT"))
        self.retries = int(retries if retries is not None
                           else config.env_int("DKTPU_NET_RETRIES"))
        self.backoff = float(backoff if backoff is not None
                             else config.env_float("DKTPU_NET_BACKOFF"))
        self.auto_rejoin = auto_rejoin
        #: requested data-plane features; what is actually used is the
        #: join-negotiated subset (:attr:`codec` / :attr:`active_shards`).
        self.shards = max(1, int(shards if shards is not None
                                 else config.env_int("DKTPU_NET_SHARDS")))
        requested = compress if compress is not None else wire.net_codec()
        if requested not in wire.CODECS:
            raise ValueError(f"unknown codec {requested!r}; "
                             f"known: {list(wire.CODECS)}")
        self.requested_codec = requested
        transport = transport if transport is not None else shm.transport_mode()
        if transport not in shm.TRANSPORTS:
            raise ValueError(f"unknown transport {transport!r}; "
                             f"known: {list(shm.TRANSPORTS)}")
        #: requested transport dialect (``DKTPU_NET_TRANSPORT``); the ring
        #: is used only when the join reply advertises a same-boot-id shm
        #: endpoint — anything else silently stays on TCP.
        self.transport = transport
        _validate_knob_combo(requested, transport, self.shards)
        #: negotiated at join; until then the PR 4 dialect (f32, 1 conn).
        self.codec = wire.CODEC_NONE
        self.active_shards = 1
        #: the server's advertised ring endpoint when the same-host check
        #: passed (``{"boot_id", "uds"}``), else None (TCP dialect).
        self.shm_info: Optional[dict] = None
        #: the server's advertised device-mesh dispatch when the
        #: same-runtime check passed (``{"proc", "token", ...}``), else
        #: None. Set only under ``transport="mesh"`` against a same-process
        #: peer; a mesh failure sweeps it (one strike — a lost device mesh
        #: does not heal) and the client demotes to its ALSO-negotiated
        #: shm/TCP dialect without dropping the in-flight window.
        self.mesh_info: Optional[dict] = None
        self.lease_s: Optional[float] = None
        #: the primary epoch the last join adopted (None until a join
        #: against an epoch-aware server); rides in every pull/commit/
        #: heartbeat header so a promoted standby can fence the stale
        #: lineage and a zombie ex-primary can fence ITSELF on sight of a
        #: higher epoch.
        self.epoch: Optional[int] = None
        self._conns = [_Conn() for _ in range(self.shards)]
        self._pool: Optional[ThreadPoolExecutor] = None
        #: tensor-index stripes per shard, from the joined center's shapes.
        self._stripes: Optional[list] = None
        #: int8 error-feedback residual, one f32 array per delta tensor.
        self._residual: Optional[list] = None
        self._seq = -1
        self._closed = False
        #: times this client re-joined after an eviction (worker loops
        #: watch it to re-adopt the center on rejoin).
        self.rejoin_count = 0
        #: times the endpoint walker moved off an endpoint (failover in
        #: progress); the tuner's apply path reads it to DEFER a mid-walk
        #: retune — the rejoin renegotiates the dialect anyway.
        self.walk_count = 0
        #: extra header fields merged into EVERY join (including the
        #: auto-rejoin after an eviction/fence — an attribute, not a join()
        #: parameter, precisely so rejoins keep carrying it). The sharded
        #: client rides its shard identity + plan hash here.
        self._join_extra: dict = {}
        #: the last join reply's ``caps`` (the server's full capability
        #: advertisement, including any ``sharding`` identity) and the last
        #: ``plan_hash`` any reply echoed — the sharded client's
        #: cross-check surface.
        self.peer_caps: Optional[dict] = None
        self.peer_plan_hash: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._closed = True
        for conn in self._conns:
            self._disconnect(conn)
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self) -> "PSClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _connect(self, conn: _Conn, deadline: float) -> socket.socket:
        if conn.sock is not None:
            return conn.sock
        from distkeras_tpu import telemetry

        if conn.ever_connected and conn.dialect == "tcp":
            telemetry.counter("netps.reconnects").add(1)
        # The connect spends from the SAME per-attempt budget as the send
        # and reply (the documented contract): against a SYN-blackholing
        # partition, connect-then-wait must not cost 2x the deadline.
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("deadline exceeded before connect")
        sock = socket.create_connection(self._current_endpoint(),
                                        timeout=remaining)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.sock = sock
        conn.ever_connected = True
        conn.dialect = "tcp"
        return sock

    @property
    def active_transport(self) -> str:
        """The dialect the data connections speak right now."""
        if self.mesh_info is not None:
            return "mesh"
        return "shm" if self.shm_info is not None else "tcp"

    @property
    def _endpoints(self) -> list:
        """Ordered (host, port) failover list (compat alias onto the
        shared :class:`EndpointWalker`)."""
        return self._walker.endpoints

    @property
    def _ep_idx(self) -> int:
        return self._walker.index

    def _current_endpoint(self) -> tuple[str, int]:
        return self._walker.current()

    def _walk_endpoints(self, seen_idx: int) -> None:
        """Advance to the next endpoint after a failure observed against
        ``seen_idx`` (the walker's CAS, under the shared fallback lock, so
        N stripe threads failing together advance ONE step, not N).
        Walking drops every connection and any ring attachment — the next
        endpoint is a different process; nothing negotiated with the old
        one survives."""
        from distkeras_tpu import telemetry

        def teardown():
            # Runs under _fallback_lock: the walker wraps on_walk in its
            # shared lock, which IS that lock (see __init__) — the
            # analyzer can't see through the callback indirection.
            self.shm_info = None  # dk: disable=DK202
            # The next endpoint is a different process: no device mesh of
            # ours lives there (the same-runtime check would fail anyway).
            self.mesh_info = None  # dk: disable=DK202
            self.walk_count += 1
            for conn in self._conns:
                self._disconnect(conn)

        if self._walker.walk(seen_idx, on_walk=teardown):
            telemetry.counter("netps.endpoint_walks").add(1)

    @staticmethod
    def _disconnect(conn: _Conn) -> None:
        # Concurrent callers (the shm->TCP fallback sweeps EVERY conn from
        # whichever stripe thread failed first; siblings disconnect their
        # own) must never None-deref: snapshot-and-null, then close — a
        # double close is benign (sock.close and Slot.close are
        # idempotent), a close-after-null is impossible.
        sock, conn.sock = conn.sock, None
        ring, conn.ring = conn.ring, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if ring is not None:
            ring.close()

    def _connect_ring(self, conn: _Conn, uds: str,
                      deadline: float) -> shm.ShmConnection:
        if conn.ring is not None:
            return conn.ring
        from distkeras_tpu import telemetry

        if conn.dialect == "shm":
            telemetry.counter("netps.reconnects").add(1)
        elif conn.ever_connected:
            # Routine post-join TCP->ring upgrade on a healthy run: its own
            # counter, NOT reconnects (documented as failure evidence).
            telemetry.counter("netps.shm_upgrades").add(1)
        # Attach (UDS connect + segment creation + fd passing) spends from
        # the same per-attempt budget as the doorbell round trip.
        ring = shm.ShmConnection(uds, deadline - time.monotonic())
        conn.ring = ring
        # A sibling's fallback sweep may have run while we attached; its
        # sweep nulls shm_info BEFORE iterating conns, so re-checking after
        # publishing the ring guarantees one side closes it — otherwise the
        # segments + the server's handler thread would outlive the upgrade
        # (this conn only ever speaks TCP after the sweep).
        if self.shm_info is None:
            self._disconnect(conn)
            raise ConnectionError("shm fallback engaged during ring attach")
        conn.ever_connected = True
        conn.dialect = "shm"
        return ring

    def _shard_pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.active_shards,
                thread_name_prefix="netps-stripe")
        return self._pool

    # -- the guarded RPC core ----------------------------------------------
    def _rpc(self, op: str, header: dict, arrays: Sequence = (),
             conn_idx: int = 0) -> tuple[dict, list]:
        if self._closed:
            raise ServerClosedError(f"client to {self.endpoint} is closed")
        from distkeras_tpu import telemetry

        conn = self._conns[conn_idx]
        attempts = self.retries + 1
        # Failover patience: with standbys configured, the retry budget
        # must bridge the PROMOTION window, not just a flaky frame — the
        # standby only takes over after the primary's lease lapses, and
        # with default knobs the attempt budget alone (~1.5 s) would give
        # up ~one lease before anyone is primary again. So multi-endpoint
        # clients keep walking until at least 2x the lease (detection +
        # promotion) + one deadline has elapsed, however many attempts
        # that takes. Single-endpoint clients keep the strict budget —
        # nothing is coming to save them, failing fast is correct.
        patience = self._walker.patience(self.lease_s, self.timeout)
        last_exc: Optional[BaseException] = None
        attempt = 0
        while True:
            conn.req += 1
            req = conn.req
            hdr = dict(header, op=op, req=req)
            if self.worker_id is not None:
                hdr.setdefault("worker_id", int(self.worker_id))
            # Per-shard RPC spans: stripe sub-RPCs are labeled by their
            # shard so the report can show per-stripe latency skew. The
            # transport dialect labels the span too (``.mesh``/``.shm``;
            # bare = TCP, the historical names) so the report CLI can
            # attribute RPC time per dialect — computed PER ATTEMPT, so
            # the TCP attempts after a mid-RPC demotion are not billed to
            # the faster dialect they fell off of.
            dialect = (".mesh" if self.mesh_info is not None
                       else ".shm" if self.shm_info is not None else "")
            label = (f"netps.rpc.{op}.s{header['shard']}{dialect}"
                     if "shard" in header else f"netps.rpc.{op}{dialect}")
            ep_seen = self._ep_idx
            try:
                with telemetry.span(label):
                    return self._attempt(conn, req, hdr, arrays)
            except NotPrimaryError as e:
                # The peer answered, but it is a standby (not yet
                # promoted) or a fenced ex-primary: retry by WALKING the
                # endpoint list — the same RPC against the next endpoint
                # (or this one, after promotion) can succeed.
                last_exc = e
                self._disconnect(conn)
                self._walk_endpoints(ep_seen)
                if not self._budget_left(attempt, attempts, patience):
                    break
                telemetry.counter("netps.retries").add(1)
                time.sleep(full_jitter(self.backoff, min(attempt, 6)))
                attempt += 1
                continue
            except (socket.timeout, ConnectionError, OSError,
                    ProtocolError) as e:
                if getattr(e, "from_reply", False):
                    raise  # the server said no; asking again won't help
                last_exc = e
                self._disconnect(conn)
                if self.mesh_info is not None:
                    # Mesh demotion is ONE strike (the shm ring retries
                    # once first; a lost device mesh does not heal): null
                    # the dispatch info and the NEXT attempt of this same
                    # RPC lands on the negotiated shm/TCP dialect with the
                    # same seq — the in-flight window rides through and
                    # the server's dedup keeps it exactly-once. Only the
                    # sweeping thread counts the demotion.
                    with self._fallback_lock:
                        swept = self.mesh_info is not None
                        if swept:
                            self.mesh_info = None
                    if swept:
                        telemetry.counter("netps.mesh.demotions").add(1)
                        telemetry.event("netps_mesh_demotion",
                                        {"why": f"{type(e).__name__}: {e}"})
                if self.shm_info is not None and (
                        attempt >= 1 or attempt + 1 == attempts):
                    # Two ring failures in a row (a transient fault retries
                    # once on the ring) — or the LAST attempt of a smaller
                    # retry budget, so a retries<=1 client still lands its
                    # NEXT rpc on TCP instead of riding a dead ring
                    # forever: the doorbell endpoint is likely gone — fall
                    # back to TCP, which the server always serves; the next
                    # join re-negotiates the upgrade. Drop EVERY
                    # connection's ring (not just this one's): stale
                    # attachments would otherwise leak segments + a server
                    # handler thread for the life of the client. Only the
                    # thread that wins the transition sweeps (a loser's
                    # sweep would close a sibling's fresh TCP socket).
                    with self._fallback_lock:
                        swept = self.shm_info is not None
                        if swept:
                            self.shm_info = None
                            for other in self._conns:
                                self._disconnect(other)
                    if swept:
                        telemetry.counter("netps.shm_fallbacks").add(1)
                # A transport failure with standbys configured also walks
                # — a dead primary never answers again, and the retransmit
                # (same seq) is exactly-once-safe wherever it lands — but
                # only once a retry against the SAME endpoint has also
                # failed (the shm-fallback rule): walking tears down every
                # stripe's connection, so a single flaky frame against a
                # healthy primary must not pay a full teardown plus a
                # wasted hop to the unpromoted standby.
                if attempt >= 1 or attempt + 1 == attempts:
                    self._walk_endpoints(ep_seen)
                if not self._budget_left(attempt, attempts, patience):
                    break
                telemetry.counter("netps.retries").add(1)
                time.sleep(full_jitter(self.backoff, min(attempt, 6)))
                attempt += 1
        telemetry.counter("netps.rpc_failures").add(1)
        if isinstance(last_exc, NotPrimaryError):
            # Every endpoint we could reach is a standby (or a fenced
            # ex-primary): surface that typed — "nobody is primary yet" is
            # actionable in a way a generic timeout is not.
            raise last_exc
        raise RPCTimeoutError(
            f"{op} to {self.endpoint} failed after {attempt + 1} attempts "
            f"(last: {type(last_exc).__name__}: {last_exc})",
            attempts=attempt + 1)

    @staticmethod
    def _budget_left(attempt: int, attempts: int,
                     patience: Optional[float]) -> bool:
        """May the retry loop go around again? The attempt budget, OR —
        multi-endpoint only — the failover patience window (the shared
        :func:`distkeras_tpu.netps.endpoints.budget_left`)."""
        return budget_left(attempt, attempts, patience)

    def _attempt(self, conn: _Conn, req: int, hdr: dict,
                 arrays: Sequence) -> tuple[dict, list]:
        """One connect + send + matched-reply receive under ONE deadline.
        The transport is whatever the join negotiated: TCP frames, or the
        same-host ring (payload in shared memory, doorbell on the UDS) —
        the deadline/matching/error contract is identical either way."""
        from distkeras_tpu import telemetry

        deadline = time.monotonic() + self.timeout
        minfo = self.mesh_info
        if minfo is not None:
            # The mesh dialect: one direct in-process call — no socket, no
            # frame, no copy. The server's dispatch enforces the identical
            # op contract (dedup, lease, fence) under its own lock; a gone
            # peer or an injected ``mesh_down`` raises ConnectionError
            # into the demotion sweep above.
            rhdr, rarrays = _mesh.dispatch(minfo["token"], hdr, list(arrays))
            err = rhdr.get("error")
            if err:
                exc = _ERROR_TYPES.get(err, NetPSError)(
                    f"{hdr['op']}: server said {err}: "
                    f"{rhdr.get('message', '')}")
                exc.from_reply = True
                raise exc
            return rhdr, rarrays
        # One read: a sibling stripe thread's shm->TCP fallback may null
        # shm_info at any point; this attempt finishes on the dialect it
        # started with (a closed ring raises the retryable taxonomy).
        info = self.shm_info
        if info is not None:
            ring = self._connect_ring(conn, info["uds"], deadline)
            ring.settimeout(max(0.001, deadline - time.monotonic()))
            sent = ring.send(wire.KIND_REQUEST, hdr, arrays)

            def set_timeout(t):
                ring.settimeout(t)

            def recv_one():
                return ring.recv()
        else:
            sock = self._connect(conn, deadline)
            sock.settimeout(max(0.001, deadline - time.monotonic()))
            sent = wire.send_frame(sock, wire.KIND_REQUEST, hdr, arrays)

            def set_timeout(t):
                sock.settimeout(t)

            def recv_one():
                prefix = wire.recv_exact(sock, wire.PREFIX_SIZE)
                return wire.finish_frame(sock, prefix)
        telemetry.counter("netps.bytes_sent").add(sent)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(f"{hdr['op']} deadline exceeded")
            set_timeout(remaining)
            kind, nbytes, rhdr, rarrays = recv_one()
            if kind != wire.KIND_REPLY:
                raise ProtocolError(f"expected a reply frame, got kind {kind}")
            if rhdr.get("req") != req:
                # A duplicated or late reply (chaos `dup`): discard and keep
                # reading — the req echo is what keeps the stream sane.
                telemetry.counter("netps.stale_replies").add(1)
                continue
            telemetry.counter("netps.bytes_received").add(nbytes)
            err = rhdr.get("error")
            if err:
                exc = _ERROR_TYPES.get(err, NetPSError)(
                    f"{hdr['op']}: server said {err}: "
                    f"{rhdr.get('message', '')}")
                # The server ANSWERED — retrying a deterministic rejection
                # burns the whole budget for the same answer. ProtocolError
                # is otherwise retryable (a corrupt frame heals on a fresh
                # connection); this flag tells _rpc the difference.
                exc.from_reply = True
                raise exc
            return rhdr, rarrays

    def _stamped(self, header: dict) -> dict:
        """Stamp the adopted epoch into a member-op header (no-op against
        pre-epoch servers — we never claim an epoch we were not given)."""
        if self.epoch is not None:
            header["epoch"] = self.epoch
        return header

    # -- distributed tracing (telemetry/tracing/) ----------------------------
    def _trace_peer(self) -> bool:
        """Whether the joined peer advertised ``CAPS["tracing"]`` — the
        gate on every trace/clock header field. A peer that never said the
        bit is sent zero new bytes (absent JSON key = absent wire byte)."""
        return bool((self.peer_caps or {}).get("tracing"))

    def _traced(self, header: dict) -> dict:
        """Attach the ambient trace context to an outgoing header (no-op
        with tracing off, outside any scope, or against an untraced peer)."""
        if self._trace_peer():
            header.update(tracing.wire_fields())
        return header

    def _rpc_traced(self, ctx, op: str, header: dict, arrays: Sequence = (),
                    conn_idx: int = 0) -> tuple[dict, list]:
        """One stripe sub-RPC under the captured trace context: pool
        threads do not inherit thread-locals, so the fan-out captures the
        commit/pull root and re-establishes it here, giving every stripe
        its own ``<op>.wire`` child span carrying the wire fields."""
        with tracing.adopt(ctx):
            with tracing.child_scope(f"{op}.wire",
                                     shard=header.get("shard")):
                return self._rpc(op, self._traced(header), arrays, conn_idx)

    def _clock_stamp(self, header: dict):
        """Stamp ``ct0`` (this clock's send time) for the NTP-style
        exchange — only against a peer that already proved it speaks the
        tracing dialect. Returns the stamp for :func:`observe_reply`."""
        if not (tracing.enabled() and self._trace_peer()):
            return None
        ct0 = time.time()
        header["ct0"] = ct0
        return ct0

    # -- striping helpers ---------------------------------------------------
    def _compute_stripes(self, template: Sequence[np.ndarray]) -> None:
        """Byte-balanced greedy stripe assignment of tensor indices over the
        active shard connections, from the joined center's shapes.
        Deterministic; the indices ride in every stripe header, so the
        server never recomputes it."""
        n = min(self.active_shards, max(1, len(template)))
        if n <= 1:
            self._stripes = None
            return
        order = sorted(range(len(template)),
                       key=lambda i: (-int(np.asarray(template[i]).nbytes), i))
        loads = [0] * n
        stripes: list = [[] for _ in range(n)]
        for i in order:
            s = loads.index(min(loads))
            stripes[s].append(i)
            loads[s] += int(np.asarray(template[i]).nbytes)
        for st in stripes:
            st.sort()
        self._stripes = stripes

    def _striped(self) -> bool:
        return (self.active_shards > 1 and self._stripes is not None
                and len(self._stripes) > 1)

    def _gather(self, futures: list) -> list:
        """Results of stripe futures; waits for ALL (no socket left with an
        in-flight reply), then re-raises the highest-priority failure —
        lease expiry beats transport errors (the caller's rejoin handles
        it; a retry cannot)."""
        results, errors = [], []
        for f in futures:
            try:
                results.append(f.result())
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors.append(e)
        if errors:
            for e in errors:
                if isinstance(e, LeaseExpiredError):
                    raise e
            raise errors[0]
        return results

    # -- RPC surface --------------------------------------------------------
    def join(self, init: Optional[Sequence[np.ndarray]] = None,
             ) -> tuple[list, int]:
        """Become (or re-become) a member; returns ``(center, updates)``.
        ``init`` seeds an uninitialized server (first joiner wins; later
        inits are ignored — everyone adopts the server's center). The
        join reply's advertised capabilities select the wire dialect
        (codec + striping) for every later pull/commit. ``_join_extra``
        fields (the sharded client's shard identity + plan) ride on every
        join, auto-rejoins included."""
        join_hdr = dict(self._join_extra, caps=wire.CAPS)
        # The clock exchange rides only once the peer has PROVED the
        # tracing dialect (a previous join's caps) — the first join of a
        # fresh client stays byte-identical to an untraced one; rejoins
        # and heartbeats carry the estimate forward.
        ct0 = self._clock_stamp(join_hdr)
        hdr, center = self._rpc(wire.OP_JOIN, join_hdr, list(init or ()))
        if ct0 is not None:
            _traceclock.observe_reply(ct0, hdr, time.time())
        self.worker_id = int(hdr["worker_id"])
        self.lease_s = hdr.get("lease_s")
        # A join ADOPTS the server's epoch (a failover re-join is exactly
        # this client arriving with a stale lineage); pre-epoch servers
        # never send one and this client then never claims one.
        self.epoch = (int(hdr["epoch"]) if hdr.get("epoch") is not None
                      else None)
        caps = hdr.get("caps") or {}
        self.peer_caps = caps
        sharding = caps.get("sharding")
        self.peer_plan_hash = (sharding.get("plan_hash")
                               if isinstance(sharding, dict) else None)
        self.codec = (self.requested_codec
                      if self.requested_codec in caps.get("codecs", ())
                      else wire.CODEC_NONE)
        self.active_shards = self.shards if caps.get("striping") else 1
        self._compute_stripes(center)
        # Same-host transport upgrade: only when this client asked for shm
        # AND the server advertised a ring endpoint AND the boot ids match
        # (actually-the-same-kernel, not just the same hostname). Every
        # other combination — old server (no caps / boolean bit), cross
        # host, tcp mode — stays on the TCP dialect with zero behavior
        # change. A re-join that lands on a different answer (e.g. a
        # restarted TCP-only server) tears the stale connections down.
        adv = caps.get("shm")
        # A mesh client negotiates the ring TOO: it is the demotion target
        # (mesh -> shm -> TCP) — losing the device mesh must not mean
        # falling all the way to sockets when the ring is one step down.
        info = (adv if self.transport in ("shm", "mesh")
                and isinstance(adv, dict)
                and adv.get("uds") and adv.get("boot_id") == shm.local_boot_id()
                and shm.endpoint_visible(adv["uds"])
                else None)
        # Same-runtime mesh upgrade: only when this client asked for mesh
        # AND the server's live advertisement proves the SAME jax runtime
        # (same boot, same process — device buffers do not cross either).
        madv = caps.get("mesh")
        minfo = (madv if self.transport == "mesh" and isinstance(madv, dict)
                 and madv.get("token")
                 and madv.get("proc") == _mesh.local_mesh_id()
                 else None)
        with self._fallback_lock:  # vs a concurrent fallback sweep
            if (info is None) != (self.shm_info is None):
                for conn in self._conns:
                    self._disconnect(conn)
            self.shm_info = info
            upgraded = minfo is not None and self.mesh_info is None
            self.mesh_info = minfo
        if upgraded:
            from distkeras_tpu import telemetry

            telemetry.counter("netps.mesh.upgrades").add(1)
        # Error feedback restarts on every (re)join: the residual belongs
        # to the window lineage the rejoin just discarded.
        self._residual = None
        # Resume the commit sequence past what the server already folded
        # from this worker_id: a restarted worker process starts at seq -1,
        # and without adopting the server's high-water mark every commit of
        # the new incarnation would be deduped away as a "retransmit".
        server_seq = int(hdr.get("last_seq", -1))
        if server_seq > self._seq:
            self._seq = server_seq
        return center, int(hdr["updates"])

    def adopt_dialect(self, other: "PSClient",
                      template: Sequence[np.ndarray]) -> None:
        """Adopt another client's join-negotiated dialect (codec, striping,
        transport) without a join of our own — membership is by worker_id,
        not by connection. The overlap loop's pull-prefetch client uses
        this so both lanes speak the same wire."""
        self.codec = other.codec
        self.active_shards = other.active_shards
        self.epoch = other.epoch
        with self._fallback_lock:  # vs a concurrent fallback sweep
            self.shm_info = other.shm_info
            self.mesh_info = other.mesh_info
        self._compute_stripes(template)

    # -- self-tuning surface (netps/tuner/) ---------------------------------
    def probe(self, arrays: Sequence[np.ndarray],
              codec: Optional[str] = None) -> Optional[dict]:
        """One timed micro-A/B round trip under ``codec`` (default: the
        negotiated one): the payload travels and is decoded exactly like a
        commit, but the server's ``probe`` op never touches the fold, the
        journal, or the dedup table. Returns the reply header, or None
        when the joined peer does not speak the probe dialect (no
        ``tuner`` caps bit / codec not advertised) — old peers are left
        alone by construction."""
        caps = self.peer_caps or {}
        if not caps.get("tuner"):
            return None
        use = codec if codec is not None else self.codec
        if use != wire.CODEC_NONE and use not in caps.get("codecs", ()):
            return None
        items: list = []
        for a in arrays:
            a = np.ascontiguousarray(a, np.float32)
            if use == wire.CODEC_NONE:
                items.append(a)
                continue
            encoded, extras = wire.codec_encode(a, use)
            items.append((encoded, extras) if extras else encoded)
        hdr, _ = self._rpc(wire.OP_PROBE,
                           self._stamped({"probe_codec": use}), items)
        return hdr

    def retune(self, codec: Optional[str] = None,
               shards: Optional[int] = None,
               template: Optional[Sequence[np.ndarray]] = None) -> dict:
        """Adopt a new wire dialect MID-RUN through the same state the
        join negotiation writes — membership, seq, epoch, and every
        exactly-once guarantee are untouched (a retransmit after a retune
        carries its original seq and dedups normally). Returns
        ``{knob: (old, new)}`` of what actually changed; a codec the peer
        never advertised or an out-of-range stripe count is clamped, not
        an error. The caller must have quiesced its own in-flight commits
        first (one logical commit must finish under ONE dialect)."""
        caps = self.peer_caps or {}
        changed: dict = {}
        if codec is not None and codec != self.codec:
            if codec == wire.CODEC_NONE or codec in caps.get("codecs", ()):
                changed["codec"] = (self.codec, codec)
                self.codec = codec
                # The residual belongs to the old codec's lineage; error
                # feedback restarts, exactly as on a rejoin.
                self._residual = None
                # Rejoins renegotiate from the retuned preference, not the
                # construction-time one — a failover must not undo the
                # controller's decision.
                self.requested_codec = codec
        if shards is not None:
            want = max(1, min(int(shards), len(self._conns)))
            if not caps.get("striping"):
                want = 1
            if want != self.active_shards:
                changed["shards"] = (self.active_shards, want)
                self.active_shards = want
                self.shards = max(self.shards, want)
                if template is not None:
                    self._compute_stripes(template)
                else:
                    self._stripes = None
                # The stripe pool is sized to active_shards; recreate lazily.
                pool, self._pool = self._pool, None
                if pool is not None:
                    pool.shutdown(wait=True)
        return changed

    def pull(self) -> tuple[list, int]:
        """Current center + update counter; renews the lease. An evicted
        client transparently re-joins first (``auto_rejoin``). Striped
        pulls reassemble a consistency-checked center (torn reads across a
        concurrent fold are detected via the echoed counters and
        re-pulled)."""
        try:
            with tracing.trace_scope("pull", wid=self.worker_id):
                if self._striped():
                    return self._striped_pull()
                with tracing.child_scope("pull.wire"):
                    hdr, center = self._rpc(
                        wire.OP_PULL, self._traced(self._stamped({})))
        except (LeaseExpiredError, EpochFencedError) as e:
            # Fenced reads exactly like evicted: the old lineage is gone;
            # re-join (walking to the promoted primary) and adopt.
            if isinstance(e, EpochFencedError):
                tracing.flight_dump("epoch_fenced")
            if not self.auto_rejoin:
                raise
            self.rejoin_count += 1
            return self.join()
        if hdr.get("plan_hash") is not None:
            # A shard server re-proves its plan identity on every pull;
            # keep the latest so the sharded client can cross-check.
            self.peer_plan_hash = hdr["plan_hash"]
        return center, int(hdr["updates"])

    def _striped_pull(self) -> tuple[list, int]:
        pool = self._shard_pool()
        stripes = self._stripes
        total = sum(len(s) for s in stripes)
        ctx = tracing.current()
        for _ in range(_PULL_CONSISTENT_TRIES):
            futures = [
                pool.submit(self._rpc_traced, ctx, wire.OP_PULL,
                            self._stamped({"shard": s,
                                           "num_shards": len(stripes),
                                           "idx": idx}), (), s)
                for s, idx in enumerate(stripes)]
            replies = self._gather(futures)
            counters = {int(h["updates"]) for h, _ in replies}
            if len(counters) == 1:
                center: list = [None] * total
                for (_h, arrays), idx in zip(replies, stripes):
                    for i, a in zip(idx, arrays):
                        center[i] = a
                return center, counters.pop()
            # A fold landed between stripe reads: torn center — re-read.
            from distkeras_tpu import telemetry

            telemetry.counter("netps.pull_torn_retries").add(1)
        # Persistent contention: one unsharded pull is always consistent.
        hdr, center = self._rpc(wire.OP_PULL, self._stamped({}))
        return center, int(hdr["updates"])

    def _compress_delta(self, delta: Sequence[np.ndarray]) -> list:
        """Delta tensors -> wire items under the negotiated codec, updating
        the int8 error-feedback residual (quantization error carried into
        the NEXT commit, so the wire's bias corrects over rounds)."""
        from distkeras_tpu import telemetry

        delta = [np.ascontiguousarray(d, np.float32) for d in delta]
        telemetry.counter("netps.bytes_precompress").add(
            sum(d.nbytes for d in delta))
        if self.codec == wire.CODEC_NONE:
            return delta
        if self.codec == wire.CODEC_INT8 and self._residual is None:
            self._residual = [np.zeros_like(d) for d in delta]
        items = []
        for i, d in enumerate(delta):
            if self.codec == wire.CODEC_INT8:
                d = d + self._residual[i]
            encoded, extras = wire.codec_encode(d, self.codec)
            if self.codec == wire.CODEC_INT8:
                self._residual[i] = d - wire.codec_decode(encoded, extras)
            items.append((encoded, extras) if extras else encoded)
        return items

    def commit(self, delta: Sequence[np.ndarray], pulled_counter: int,
               seq: Optional[int] = None) -> CommitResult:
        """Fold ``delta`` (worker-normalized) into the center. The seq is
        assigned before the first transmission and reused across retries:
        a lost ACK can never double-fold. With striping, ONE seq spans all
        stripe sub-RPCs — the server assembles them and folds once. An
        explicit ``seq`` is the sharded client's one-logical-seq fan-out
        (and its dedup-safe same-seq retransmit after a per-shard
        eviction); this client's own counter only ever moves forward."""
        if seq is None:
            self._seq += 1
            seq = self._seq
        else:
            self._seq = max(self._seq, int(seq))
            seq = int(seq)
        # The trace root: one commit = one trace, client-rooted. Segments
        # recorded here (encode/wire/ack) and on every process the wire
        # fields reach (queue/fold/fsync/replicate) share its trace id.
        with tracing.trace_scope("commit", wid=self.worker_id, seq=seq):
            with tracing.child_scope("commit.encode"):
                items = self._compress_delta(delta)
            base = self._stamped({"seq": seq, "pulled": int(pulled_counter)})
            try:
                if self._striped() and len(items) == sum(
                        len(s) for s in self._stripes):
                    hdr = self._striped_commit(base, items)
                else:
                    with tracing.child_scope("commit.wire"):
                        hdr, _ = self._rpc(wire.OP_COMMIT, self._traced(base),
                                           items)
            except (LeaseExpiredError, EpochFencedError) as e:
                # Fenced commit = evicted commit: it was NEVER folded (the
                # whole point of the fence); discard the window, re-join
                # the promoted primary, continue from a fresh pull. A
                # fence is flight-recorder evidence: dump the discarded
                # lineage's last seconds before rejoining past it.
                if isinstance(e, EpochFencedError):
                    tracing.flight_dump("epoch_fenced")
                if not self.auto_rejoin:
                    raise
                self.rejoin_count += 1
                self.join()
                return CommitResult(applied=False, duplicate=False,
                                    evicted=True, updates=-1, staleness=-1)
            if hdr is None:
                # Every stripe answered ``pending``: membership churn (an
                # eviction sweep or a concurrent rejoin purging the
                # server's half-assembled stripe set) lost this commit —
                # it was NEVER folded and never will be. Same recovery as
                # an evicted commit: discard the window, refresh
                # membership + the server's pending state, continue from
                # a fresh pull.
                if not self.auto_rejoin:
                    raise NetPSError(
                        "striped commit never completed: every stripe is "
                        "pending — the server lost part of the stripe set")
                self.join()
                return CommitResult(applied=False, duplicate=False,
                                    evicted=True, updates=-1, staleness=-1)
            with tracing.child_scope("commit.ack",
                                     applied=bool(hdr.get("applied"))):
                return CommitResult(
                    applied=bool(hdr.get("applied")),
                    duplicate=bool(hdr.get("duplicate")),
                    evicted=False, updates=int(hdr["updates"]),
                    staleness=int(hdr.get("staleness", -1)))

    def _striped_commit(self, base: dict, items: list) -> Optional[dict]:
        """One logical commit over the stripe connections; returns the
        fold-outcome header, or None when every stripe came back
        ``pending`` (the server lost part of the set to membership churn —
        :meth:`commit` recovers via the evicted path)."""
        stripes = self._stripes
        pool = self._shard_pool()
        ctx = tracing.current()
        futures = [
            pool.submit(
                self._rpc_traced, ctx, wire.OP_COMMIT,
                dict(base, shard=s, num_shards=len(stripes), idx=idx),
                [items[i] for i in idx], s)
            for s, idx in enumerate(stripes)]
        replies = self._gather(futures)
        # Exactly one stripe's reply carries the fold outcome (the one that
        # completed the assembly, or the dedup answer); the rest say
        # ``pending``.
        for hdr, _ in replies:
            if hdr.get("applied"):
                return hdr
        for hdr, _ in replies:
            if hdr.get("duplicate"):
                return hdr
        return None

    def heartbeat(self) -> int:
        """Renew the lease; returns the server's update counter. A traced
        heartbeat doubles as the clock exchange's steady drumbeat — every
        renewal is another four-timestamp sample, and the min-rtt one
        wins."""
        hb = self._stamped({})
        ct0 = self._clock_stamp(hb)
        try:
            hdr, _ = self._rpc(wire.OP_HEARTBEAT, hb)
        except (LeaseExpiredError, EpochFencedError) as e:
            if isinstance(e, EpochFencedError):
                tracing.flight_dump("epoch_fenced")
            if not self.auto_rejoin:
                raise
            self.rejoin_count += 1
            _center, updates = self.join()
            return updates
        if ct0 is not None:
            _traceclock.observe_reply(ct0, hdr, time.time())
        return int(hdr["updates"])

    def stats(self, ring: int = 64) -> dict:
        """One live telemetry scrape of the peer (``CAPS`` op ``stats``):
        counters/gauges/span aggregates plus the flight ring's most recent
        ``ring`` records. Membership-free — no join, no lease, no seq —
        so any observer (the ``telemetry scrape`` CLI) can dial in."""
        hdr, _ = self._rpc(wire.OP_STATS, {"ring": int(ring)})
        return hdr

    def leave(self) -> None:
        """Best-effort clean departure (a dead server is not an error —
        leaving was the goal)."""
        try:
            self._rpc(wire.OP_LEAVE, {})
        except (NetPSError, OSError):
            pass
