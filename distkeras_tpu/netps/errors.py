"""Typed failure taxonomy of the networked parameter server.

Every way a ``netps`` RPC can fail is one of these, so worker loops and
tests match on type — never on message strings. All of them subclass
:class:`~distkeras_tpu.resilience.errors.ResilienceError`: the network
transport is part of the resilience surface, and the Supervisor's default
``retry_on=(Exception,)`` already covers it.
"""

from __future__ import annotations

from distkeras_tpu.resilience.errors import ResilienceError


class NetPSError(ResilienceError):
    """Base class for every networked-parameter-server failure."""


class ProtocolError(NetPSError):
    """A frame violated the wire contract: bad magic, unsupported version,
    checksum mismatch, oversized length, or a truncated body. The receiving
    side must tear the connection down — after a framing error the byte
    stream can never be trusted to re-align."""


class RPCTimeoutError(NetPSError):
    """An RPC exhausted its deadline *and* its retry budget. Carries the
    number of attempts made so callers (and tests) can see the budget was
    really spent, not skipped."""

    def __init__(self, message: str, attempts: int = 0):
        super().__init__(message)
        self.attempts = attempts


class ServerDrainingError(NetPSError):
    """The server is draining (``close()`` was called): it no longer accepts
    commits. Deliberately **not retryable** — a draining server never comes
    back, so the client surfaces this to the worker loop immediately."""


class LeaseExpiredError(NetPSError):
    """The server evicted this worker (its lease expired) before the RPC
    arrived. The hardened client reacts by re-joining; the worker loop
    discards the in-flight window and continues from a fresh pull."""


class EpochFencedError(NetPSError):
    """The commit carried a primary epoch the server no longer honors: a
    standby promoted and fenced the old lineage (stale client epoch), or
    this server itself was fenced by a higher epoch (it is the zombie).
    The hardened client reacts like an eviction — re-join (walking the
    endpoint list to the promoted primary), adopt the new epoch, discard
    the stale window. Never folded: the whole point is zero stale-epoch
    folds after a failover."""


class ShardPlanError(ProtocolError):
    """A sharded-center plan violation: a peer without the ``sharding``
    capability joined a shard server, a join carried no partition plan, or
    the joiner's plan hash does not match the shard set's. Subclasses
    :class:`ProtocolError` because it is one — a contract violation the
    server answers typed at join time, so a mismatched (or plan-unaware)
    client can never fold a partial plan silently."""


class NotPrimaryError(NetPSError):
    """The peer answered but is not the primary: a warm standby that has
    not (yet) promoted, or a fenced ex-primary. Retryable *by walking the
    endpoint list* — the same RPC against the next endpoint (or this one
    after promotion) can succeed, so the client treats it like a transport
    failure rather than a terminal rejection."""


class ServerClosedError(NetPSError):
    """A parameter-server object (networked or the in-process raced twin)
    was used after ``close()``. Worker threads blocked on it must exit,
    not commit into a dead center forever."""
