"""The ``mesh`` transport dialect: a device-resident center.

Every dialect before this one (TCP frames, the shm ring) bottoms out in a
host-side fold — even the in-process raced twin round-trips host memory on
every commit. This dialect is the paper's stated north star (replace the
socket parameter server with ICI collectives) grafted onto the netps
contract instead of replacing it:

* **The center lives on device.** :class:`MeshFolder` holds the center as
  jax buffers laid out over a one-axis device mesh (``("fold",)``), each
  tensor's :class:`~jax.sharding.PartitionSpec` derived from the SAME
  :class:`~distkeras_tpu.netps.shards.PartitionPlan` the sharded wire
  plane uses (``plan.to_partition_specs()`` — one plan, two fabrics) and
  clamped by :func:`distkeras_tpu.parallel.sharding.restrict_spec`.
* **Folds are collectives.** One ``jax.jit(donate_argnums=0)`` program
  per codec signature folds the whole delta: a ``shard_map`` body adds
  each device's rows in place (donation means the old center buffers are
  consumed, not copied — the zero-copy fold), dequantization fused via
  the SAME Pallas kernel the host path uses
  (:func:`distkeras_tpu.ops.pallas.fold.fold_traced` — on TPU compiled,
  in tests interpreted), and a ``psum`` over per-device element counts is
  the cross-shard conservation check.
* **The dialect is negotiated, not assumed.** A mesh server advertises
  ``caps["mesh"] = {"proc": <boot_id:pid>, "token": ...}`` in its join
  reply; a client requesting ``DKTPU_NET_TRANSPORT=mesh`` upgrades only
  when the proc token matches :func:`local_mesh_id` — devices are
  shareable only within ONE jax runtime, so the same-runtime check is the
  shm boot-id check one level up. Everyone else stays on the wire.
* **Every durability guarantee is host-authoritative and rides through.**
  The request still crosses :meth:`PSServer._serve_frame` (dedup, epoch
  fence, lease, membership — unchanged), and every device fold's
  ``(wid, seq, staleness, epoch)`` record still enqueues into the bounded
  background journal writer. Recovery replays the journal host-side and
  re-seats the recovered center on device — bit-identical, because the
  collective body mirrors ``fold_compressed_numpy`` term for term.
* **Demotion, not failure.** A lost mesh (device loss, closed server,
  injected ``mesh_down``) demotes the client to its negotiated shm/TCP
  dialect without dropping the in-flight window — the retransmit keeps
  its seq and the dedup table makes it exactly-once; a mesh server serves
  the shm ring and TCP concurrently precisely so the demotion has
  somewhere to land. The shm->TCP fallback pattern, one level up.

Dispatch itself is a direct in-process call (no frames, no sockets, no
copies): the client hands its wire-form delta — the same ``(array, spec)``
pairs a frame would carry — straight to the server's dispatch under the
server's own lock discipline. That handoff is what lets bench #8's
``mesh`` arm meet the in-process baseline while keeping journal + dedup +
fence semantics identical to the socket dialects.
"""

from __future__ import annotations

import os
import threading
import uuid
import warnings
from typing import Optional, Sequence

import numpy as np

from distkeras_tpu.netps import shm, wire
from distkeras_tpu.resilience import faults as _faults

#: the one mesh axis every center tensor folds over.
MESH_AXIS = "fold"


def local_mesh_id() -> str:
    """The same-runtime identity for mesh negotiation: device buffers are
    shareable only within one jax runtime, i.e. one process on one kernel
    — so the token is the shm boot-id check narrowed by pid."""
    return f"{shm.local_boot_id()}:{os.getpid()}"


def mesh_available() -> bool:
    """Whether this process can host a device-resident center at all
    (jax importable and at least one device). Never raises."""
    try:
        import jax

        return len(jax.devices()) > 0
    except Exception:
        return False


# ---------------------------------------------------------------------------
# The in-process dispatch registry
# ---------------------------------------------------------------------------
#
# A mesh server registers its serve function under an opaque token and
# advertises the token in its join reply. Dispatch is the whole data path:
# the client's handler thread calls the server's transport-independent
# dispatch directly (the server's center lock serializes folds exactly as
# it does for socket handler threads). A token that is gone — server
# closed, process restarted — raises ConnectionError, which is precisely
# the failure class the client's demotion sweep catches.

_REG_LOCK = threading.Lock()
_SERVERS: dict = {}


def register(serve_fn) -> str:
    """Register a mesh server's serve function; returns its token."""
    token = uuid.uuid4().hex
    with _REG_LOCK:
        _SERVERS[token] = serve_fn
    return token


def unregister(token: Optional[str]) -> None:
    with _REG_LOCK:
        _SERVERS.pop(token, None)


def dispatch(token: str, header: dict, arrays: list):
    """One direct request against a registered mesh server: returns the
    ``(reply_header, reply_arrays)`` pair a wire frame would have carried.
    Raises ``ConnectionError`` when the peer is gone or when the
    ``mesh_down`` fault drill fires — both look like device loss to the
    caller, and both must trigger demotion, not an error reply."""
    with _REG_LOCK:
        fn = _SERVERS.get(token)
    if fn is None:
        raise ConnectionError("mesh peer is gone (server closed)")
    plan = _faults.active_net_plan()
    if plan is not None and header.get("op") == wire.OP_COMMIT:
        if plan.fire("mesh_down", int(header.get("seq", 0))) is not None:
            raise ConnectionError("injected mesh_down: device mesh lost")
    served = fn(dict(header), list(arrays))
    if served is None:
        raise ConnectionError("mesh peer refused the request")
    return served


# ---------------------------------------------------------------------------
# The device-resident center
# ---------------------------------------------------------------------------

class MeshFolder:
    """The center as donated device buffers, folded by collectives.

    Construction seats ``center`` (host f32 arrays) on the process's
    devices under per-tensor shardings; :meth:`fold` consumes a wire-form
    delta (plain arrays or ``(array, spec)`` codec pairs) through one
    jitted, buffer-donating collective program; :meth:`center_host` is
    the lazily-synced host mirror every read path (pull replies, join
    inits, snapshots, replication) goes through. NOT thread-safe — the
    server's center lock already serializes every caller.
    """

    def __init__(self, center: Sequence[np.ndarray], *, plan=None,
                 interpret: Optional[bool] = None):
        import jax
        from jax.sharding import Mesh, NamedSharding

        devices = jax.devices()
        if not devices:  # pragma: no cover - jax without devices
            raise RuntimeError("no jax devices for a mesh center")
        self.backend = devices[0].platform
        self.num_devices = len(devices)
        #: interpret=True forces the fused Pallas-kernel body under the
        #: interpreter off-TPU — the CI fold-parity hook (same kernel,
        #: same collective body a real chip runs). The default off-TPU is
        #: the exact two-program formulation instead (see the fold
        #: section below), which is bit-identical to the numpy oracle.
        self.interpret = bool(interpret)
        self._mesh = Mesh(np.asarray(devices), (MESH_AXIS,))
        self._shapes = [tuple(np.shape(a)) for a in center]
        specs = self._tensor_specs(plan)
        self._specs = specs
        self._shardings = [NamedSharding(self._mesh, s) for s in specs]
        # (np.ascontiguousarray would promote 0-d tensors to 1-d; the
        # reshape pins every recorded shape instead.)
        self._center = [
            jax.device_put(np.asarray(a, np.float32).reshape(s), sh)
            for a, sh, s in zip(center, self._shardings, self._shapes)]
        self._host: Optional[list] = [
            np.asarray(a, np.float32).reshape(s).copy()
            for a, s in zip(center, self._shapes)]
        #: expected psum'd element count per fold: a sharded tensor's
        #: shards sum to its size; a replicated tensor counts once per
        #: device (each folds its full copy) — any other total means a
        #: device shard went missing.
        self._expected = 0
        for sp, s in zip(specs, self._shapes):
            elems = int(np.prod(s, dtype=np.int64)) if s else 1
            self._expected += (elems if self._sharded_spec(sp)
                               else self.num_devices * elems)
        self.folds = 0
        self._fold_fns: dict = {}
        self._scale_fns: dict = {}
        self._add_fn = None

    # -- layout --------------------------------------------------------
    @staticmethod
    def _sharded_spec(spec) -> bool:
        return any(a is not None for a in spec)

    def _tensor_specs(self, plan) -> list:
        """Per-tensor PartitionSpecs: the wire plan's rules when given
        (``to_partition_specs`` — one plan for both fabrics), else shard
        axis 0 where the device count divides it; either way clamped by
        the shared ``restrict_spec`` so a ragged dim degrades to
        replicated instead of erroring."""
        from jax.sharding import PartitionSpec as P

        from distkeras_tpu.parallel.sharding import restrict_spec

        if plan is not None and len(plan.names) == len(self._shapes):
            base = [spec for _pat, spec in plan.to_partition_specs(MESH_AXIS)]
        else:
            base = [P(MESH_AXIS) if s and int(s[0]) >= self.num_devices
                    else P() for s in self._shapes]
        return [restrict_spec(sp, self._mesh, shape=s)
                for sp, s in zip(base, self._shapes)]

    # -- the collective fold -------------------------------------------
    #
    # Two formulations, one semantics:
    #
    # * **fused** (real TPUs, and interpret mode for the CI fold-parity
    #   job): ONE program — a shard_map body running the Pallas
    #   dequant+accumulate kernel per tensor shard. Parity with the numpy
    #   oracle is allclose-tight, the same bar the host Pallas path is
    #   held to (``tests/test_pallas_fold.py``): within one compiled
    #   program the multiply+add may contract to an FMA.
    # * **exact** (the CPU default): TWO programs — dequant·scale, then a
    #   donated collective add. The program boundary forces the product
    #   to round to f32 before the accumulate (XLA contracts mul+add into
    #   an FMA *within* a program, keeping the unrounded product — no
    #   barrier fences it), which makes the fold BIT-IDENTICAL to
    #   ``fold_compressed_numpy``. Uncompressed unit-scale commits (the
    #   hot adag path) skip the first program outright.

    def _build_scale(self, codecs: tuple):
        import jax
        import jax.numpy as jnp
        from jax import lax

        def dequant(q, s, codec):
            if codec is None:
                return s * q
            if codec == "int8":
                return s * q.astype(jnp.float32)
            return s * lax.bitcast_convert_type(
                q.astype(jnp.uint32) << jnp.uint32(16), jnp.float32)

        def scale_all(deltas, scales):
            return [dequant(q, s, codec)
                    for q, s, codec in zip(deltas, scales, codecs)]

        return jax.jit(scale_all)

    def _build_add(self, codecs):
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from distkeras_tpu.ops.pallas import fold as pallas_fold

        n = len(self._shapes)
        specs = tuple(self._specs)
        interpret = self.interpret
        fused = codecs is not None

        def tensor_fold(c, q, s, codec):
            if not fused or codec is None:
                return c + s * q if fused else c + q
            return pallas_fold.fold_traced(c, q, s, codec=codec,
                                           interpret=interpret)

        def body(*flat):
            center = flat[:n]
            deltas = flat[n:2 * n]
            scales = flat[2 * n:] if fused else (None,) * n
            cods = codecs if fused else (None,) * n
            out = [tensor_fold(c, q, s, codec) for c, q, s, codec
                   in zip(center, deltas, scales, cods)]
            counted = sum(int(np.prod(c.shape, dtype=np.int64)) or 1
                          for c in center)
            folded = jax.lax.psum(jnp.int32(counted), MESH_AXIS)
            return tuple(out) + (folded,)

        scalar = tuple(P() for _ in range(n)) if fused else ()
        mapped = shard_map(
            body, mesh=self._mesh,
            in_specs=specs + specs + scalar,
            out_specs=specs + (P(),),
            # pallas_call inside the body: replication checking must be off.
            check_rep=False)

        def fold_all(center, deltas, scales=()):
            return mapped(*center, *deltas, *scales)

        return jax.jit(fold_all, donate_argnums=(0, 1))

    def fold(self, delta: Sequence, scale: float) -> None:
        """Fold one wire-form commit into the device center. ``scale`` is
        the discipline's commit scale; per-tensor codec scales fold in
        exactly as the numpy reference folds them. Any failure leaves the
        center untouched (the programs are functional: nothing mutates
        until the donated program returns) — the server demotes to the
        host fold on exception."""
        import jax
        import jax.numpy as jnp

        from distkeras_tpu.netps import wire
        from distkeras_tpu.netps.fold import split_entry

        if len(delta) != len(self._center):
            raise ValueError(
                f"delta has {len(delta)} tensors, center {len(self._center)}")
        fused = self.backend == "tpu" or self.interpret
        arrs, scales, codecs = [], [], []
        for entry, shape in zip(delta, self._shapes):
            a, spec = split_entry(entry)
            codec = spec.get("codec") if spec else None
            if codec == wire.CODEC_INT8:
                s = float(scale) * float(spec["scale"])
                a = np.asarray(a, np.int8).reshape(shape)
            elif codec == wire.CODEC_BF16:
                s = float(scale)
                a = np.asarray(a, np.uint16).reshape(shape)
            else:
                codec = None
                s = float(scale)
                a = np.asarray(a, np.float32).reshape(shape)
                if not fused and s != 1.0:
                    # Exact mode scales UNCOMPRESSED tensors host-side:
                    # one numpy multiply rounds ``s*q`` to f32 exactly
                    # as the device scale program would (both round the
                    # product once), and when the whole commit is
                    # uncompressed — the hot f32 path — the scale
                    # program is skipped outright.
                    a = a * np.float32(s)
                    s = 1.0
            arrs.append(a)
            scales.append(np.float32(s))
            codecs.append(codec)
        key = tuple(codecs)
        deltas = [jax.device_put(a, sh)
                  for a, sh in zip(arrs, self._shardings)]
        jscales = [jnp.float32(s) for s in scales]
        with warnings.catch_warnings():
            # CPU ignores donation with a UserWarning; the fold is still
            # correct (just copying), and TPU honors it.
            warnings.simplefilter("ignore")
            if fused:
                fn = self._fold_fns.get(key)
                if fn is None:
                    fn = self._fold_fns[key] = self._build_add(key)
                out = fn(list(self._center), deltas, jscales)
            else:
                if any(c is not None for c in codecs) or \
                        any(float(s) != 1.0 for s in scales):
                    sfn = self._scale_fns.get(key)
                    if sfn is None:
                        sfn = self._scale_fns[key] = self._build_scale(key)
                    deltas = sfn(deltas, jscales)
                fn = self._add_fn
                if fn is None:
                    fn = self._add_fn = self._build_add(None)
                out = fn(list(self._center), list(deltas))
        folded = int(out[-1])
        if folded != self._expected:
            raise RuntimeError(
                f"mesh fold conservation check: psum counted {folded} "
                f"elements, expected {self._expected} — a device shard "
                f"went missing")
        self._center = list(out[:-1])
        self._host = None
        self.folds += 1

    # -- host views ----------------------------------------------------
    def center_host(self) -> list:
        """The host f32 mirror, synced lazily (one device->host transfer
        after any number of folds, not one per fold). Callers copy before
        handing rows to a reply — this list is the cache."""
        if self._host is None:
            import jax

            self._host = [
                np.asarray(jax.device_get(a), np.float32).reshape(s)
                for a, s in zip(self._center, self._shapes)]
        return self._host

    def close(self) -> None:
        self._center = []
        self._host = None
        self._fold_fns = {}
