"""The one shared server-side fold: commit discipline semantics.

Both parameter-server stand-ins — the in-process raced twin
(:class:`distkeras_tpu.racelab.RacedParameterServer`) and the networked
:class:`distkeras_tpu.netps.server.PSServer` — fold a worker's commit into
the center through THIS function, so the raced-parity evidence
(``tests/test_raced_ps.py``: raced PS vs deterministic window folds agree)
transfers to the network server by construction: same fold, different
transport.

Division of labor mirrors the reference exactly (SURVEY.md §3.3/§3.4): the
*worker* pre-normalizes its commit (ADAG divides by the window, the elastic
disciplines send ``e = α·(w − center)``), and the *server* applies one
scale — ``1/(staleness+1)`` for DynSGD, identity for everything else — and
adds. Staleness is the server's update counter minus the committer's
pull-time counter.

**Compressed-domain folds.** A delta tensor may arrive as an ``(array,
spec)`` pair in its *wire* dtype (the netps handlers read frames with
``decode=False``): int8 with a per-tensor scale, or bf16 bit-truncated.
Those fold without a decode-to-f32 pass — the dequantization is fused
into the accumulate. Two backends, one dispatch point (here, so parity
evidence stays transferable):

* a **pure-numpy reference** (CPU CI, and the default for a stdlib-only
  server process): ``center += (commit_scale · tensor_scale) · q`` in one
  fused expression;
* the **Pallas kernel** (``distkeras_tpu.ops.pallas.fold``) when jax sees
  a TPU — the dequant+accumulate as one VMEM-resident pass per tensor.
  Interpret-mode parity against the numpy reference is pinned by
  ``tests/test_pallas_fold.py`` and the CI fold-parity job.

Fold throughput is exported by the netps server as the
``netps.fold.tensors_per_sec`` gauge (docs/OBSERVABILITY.md) so the
report CLI can tell a fold-bound server from a wire-bound one.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

#: every discipline either PS stand-in accepts (the reference routed both
#: elastic trainers through the plain DeltaParameterServer — the fold is
#: identical; elasticity lives worker-side).
SUPPORTED_DISCIPLINES = ("downpour", "adag", "dynsgd", "aeasgd", "eamsgd")


def check_discipline(discipline: str) -> str:
    if discipline not in SUPPORTED_DISCIPLINES:
        raise ValueError(
            f"unsupported PS discipline {discipline!r}; "
            f"known: {list(SUPPORTED_DISCIPLINES)}")
    return discipline


def counter_scalar(counter) -> int:
    """One scalar from a possibly per-shard counter: a sharded center's
    pull/join returns one update counter PER SHARD; consumers mirroring a
    single lineage counter (the hier aggregator, the simulator's
    SimCenter) take the MIN — staleness charged from it can only be
    overstated (DynSGD then downweights, which is safe), never
    negative."""
    if isinstance(counter, (tuple, list)):
        return min(int(u) for u in counter)
    return int(counter)


def counter_staleness(updates, pulled) -> int:
    """THE staleness counter rule, shared by every center implementation
    — ``PSServer._fold_locked``, and the fleet simulator's stand-in
    center — so simulation exercises the same arithmetic production
    folds use: staleness is the server's update counter at fold time
    minus the committer's pull-time counter. Either side may arrive as a
    per-shard tuple (reduced by :func:`counter_scalar`'s MIN rule)."""
    return counter_scalar(updates) - counter_scalar(pulled)


def commit_scale(discipline: str, staleness: int) -> float:
    """The server-side scale applied to a commit folded ``staleness``
    updates after its pull (DynSGD's counter semantics; 1.0 otherwise)."""
    if discipline == "dynsgd":
        return 1.0 / (float(staleness) + 1.0)
    return 1.0


def split_entry(entry) -> tuple[np.ndarray, Optional[dict]]:
    """A delta entry is a plain ndarray (in-process callers) or an
    ``(array, spec)`` wire pair (the netps raw-decode path)."""
    if isinstance(entry, tuple):
        a, spec = entry
        return a, (spec or None)
    return entry, None


def decode_entry(entry) -> np.ndarray:
    """One delta entry -> a plain f32-domain array (the non-fold consumers:
    join inits, the hierarchical aggregator's pre-combine)."""
    from distkeras_tpu.netps import wire

    a, spec = split_entry(entry)
    return wire.codec_decode(a, spec) if spec else np.asarray(a)


def validate_delta(delta) -> bool:
    """Up-front spec validation for a commit's wire entries — the rules
    ``codec_decode`` enforced before the ``decode=False`` path existed
    (unknown codec, int8 without a scale), applied BEFORE any fold or
    bookkeeping: a spec that failed mid-:func:`fold_delta` would leave
    the already-folded prefix tensors in the center with no commit_log
    entry, and the retransmit would fold them AGAIN. Raises
    ``ProtocolError``; returns whether any entry folds in the compressed
    domain (the caller's cue to resolve the accelerator backend)."""
    from distkeras_tpu.netps import wire
    from distkeras_tpu.netps.errors import ProtocolError

    compressed = False
    for entry in delta:
        _a, spec = split_entry(entry)
        codec = spec.get("codec") if spec else None
        if not codec:
            continue
        if codec == wire.CODEC_INT8:
            try:
                float(spec["scale"])
            except (KeyError, TypeError, ValueError) as e:
                raise ProtocolError(f"int8 array spec without a scale: {e}")
        elif codec != wire.CODEC_BF16:
            raise ProtocolError(f"unknown codec {codec!r} in array spec")
        compressed = True
    return compressed


# -- compressed-domain backends ---------------------------------------------

_ACCEL = None
_ACCEL_RESOLVED = False


def _accel():
    """The on-accelerator fold backend, or None. Resolved once: the Pallas
    kernel is used only when jax is importable AND a TPU is the default
    backend — the stdlib-only server process never pays a jax import."""
    global _ACCEL, _ACCEL_RESOLVED
    if not _ACCEL_RESOLVED:
        _ACCEL_RESOLVED = True
        try:
            import jax

            if jax.default_backend() == "tpu":
                from distkeras_tpu.ops.pallas import fold as pallas_fold

                _ACCEL = pallas_fold
        except Exception:
            _ACCEL = None
    return _ACCEL


def _reset_accel() -> None:
    """Forget the resolved backend (tests swap backends per-case)."""
    global _ACCEL, _ACCEL_RESOLVED
    _ACCEL = None
    _ACCEL_RESOLVED = False


def backend_name() -> str:
    """The resolved compressed-fold backend's name, for the server stats
    scrape and the chaos smokes (which assert which arithmetic actually
    ran): ``numpy`` (the pure reference), ``pallas-tpu`` (the fused
    kernel on a real chip), ``pallas-interpret`` (the same kernel under
    the interpreter — test/parity runs that force ``_ACCEL``), or
    ``unresolved`` before the first codec'd commit resolves it. A
    device-resident center reports ``mesh`` one level up (the server
    overrides — the mesh dialect folds through its own jitted collective,
    not this dispatch point)."""
    if not _ACCEL_RESOLVED:
        return "unresolved"
    if _ACCEL is None:
        return "numpy"
    try:
        import jax

        tpu = jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - jax vanished mid-run
        tpu = False
    return "pallas-tpu" if tpu else "pallas-interpret"


def resolve_backend():
    """Resolve (and cache) the compressed-fold backend NOW; returns it (or
    None). Callers that hold a lock across :func:`fold_delta` must call
    this first, outside the lock: the first resolution imports jax and
    initializes its backend — seconds, not microseconds — and every
    pull/commit/heartbeat (i.e. every lease renewal) queues behind that
    lock meanwhile. The netps server does this per codec'd commit before
    taking its center lock; after the first call it is a bool check."""
    return _accel()


def fold_compressed_numpy(center: np.ndarray, a: np.ndarray, spec: dict,
                          scale: float) -> None:
    """The pure-numpy reference: accumulate a wire-dtype tensor into the
    f32 ``center`` in place, dequantization fused into the add. Specs are
    assumed valid (:func:`validate_delta` runs before any fold): a missing
    int8 scale raises rather than silently folding zero."""
    from distkeras_tpu.netps import wire

    codec = spec.get("codec")
    if codec == wire.CODEC_INT8:
        s = np.float32(scale * float(spec["scale"]))
        if s:
            np.add(center, a.astype(np.float32) * s, out=center)
        return
    if codec == wire.CODEC_BF16:
        # Not compressed-domain in any meaningful sense on CPU (the f32
        # temp materializes either way) — reuse the ONE bf16 dequant.
        np.add(center, np.float32(scale) * wire.codec_decode(a, spec),
               out=center)
        return
    raise ValueError(f"unknown codec {codec!r} in delta spec")


def _fold_entry(c: np.ndarray, entry, scale: float) -> None:
    a, spec = split_entry(entry)
    codec = spec.get("codec") if spec else None
    if not codec:
        c += scale * np.asarray(a, c.dtype)
        return
    accel = _accel()
    if accel is not None:
        c[...] = accel.fold_compressed(c, a, spec, float(scale))
    else:
        fold_compressed_numpy(c, np.asarray(a), spec, float(scale))


def fold_delta(center: Sequence[np.ndarray], delta: Sequence,
               discipline: str, staleness: int) -> None:
    """Fold one worker-normalized commit into ``center`` **in place** —
    the body of the reference's ``handle_commit`` under the lock. Delta
    entries may be plain arrays or ``(array, spec)`` wire pairs; codec'd
    pairs fold in the compressed domain.

    Deliberately telemetry-free: callers hold their center lock across
    this, and metrics must not nest a telemetry lock under it (DK201).
    The netps server times the call and exports
    ``netps.fold.tensors_per_sec`` after releasing its lock."""
    scale = commit_scale(discipline, staleness)
    for c, d in zip(center, delta):
        _fold_entry(c, d, scale)
