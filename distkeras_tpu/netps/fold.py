"""The one shared server-side fold: commit discipline semantics.

Both parameter-server stand-ins — the in-process raced twin
(:class:`distkeras_tpu.racelab.RacedParameterServer`) and the networked
:class:`distkeras_tpu.netps.server.PSServer` — fold a worker's commit into
the center through THIS function, so the raced-parity evidence
(``tests/test_raced_ps.py``: raced PS vs deterministic window folds agree)
transfers to the network server by construction: same fold, different
transport.

Division of labor mirrors the reference exactly (SURVEY.md §3.3/§3.4): the
*worker* pre-normalizes its commit (ADAG divides by the window, the elastic
disciplines send ``e = α·(w − center)``), and the *server* applies one
scale — ``1/(staleness+1)`` for DynSGD, identity for everything else — and
adds. Staleness is the server's update counter minus the committer's
pull-time counter.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

#: every discipline either PS stand-in accepts (the reference routed both
#: elastic trainers through the plain DeltaParameterServer — the fold is
#: identical; elasticity lives worker-side).
SUPPORTED_DISCIPLINES = ("downpour", "adag", "dynsgd", "aeasgd", "eamsgd")


def check_discipline(discipline: str) -> str:
    if discipline not in SUPPORTED_DISCIPLINES:
        raise ValueError(
            f"unsupported PS discipline {discipline!r}; "
            f"known: {list(SUPPORTED_DISCIPLINES)}")
    return discipline


def commit_scale(discipline: str, staleness: int) -> float:
    """The server-side scale applied to a commit folded ``staleness``
    updates after its pull (DynSGD's counter semantics; 1.0 otherwise)."""
    if discipline == "dynsgd":
        return 1.0 / (float(staleness) + 1.0)
    return 1.0


def fold_delta(center: Sequence[np.ndarray], delta: Sequence[np.ndarray],
               discipline: str, staleness: int) -> None:
    """Fold one worker-normalized commit into ``center`` **in place** —
    the body of the reference's ``handle_commit`` under the lock."""
    scale = commit_scale(discipline, staleness)
    for c, d in zip(center, delta):
        c += scale * np.asarray(d, c.dtype)
